"""The compute path (docs/compute.md): page-blockwise decode attention,
bf16 mixed precision, and named remat policies.

Contracts pinned here:

- the blockwise decode kernel is value-equivalent to the dense
  full-width softmax it replaces, for contiguous slot rows AND paged
  pools (GQA, ragged widths, inactive-row write-reselect included);
- dead blocks past every resident length are NEVER touched — proven by
  NaN-poisoning them (a single gathered element would poison the
  output) and by the ``resident_blocks`` trip-count formula;
- a fully-masked visited block contributes exact zeros (the finite
  ``_MASK`` sentinel + explicit probability zeroing — the NaN hazard
  ``-inf`` masking would reintroduce);
- softmax statistics stay float32 under bf16 inputs in both
  ``dense_attention`` and the blockwise kernel (the f32-stats
  contract the mixed-precision mode relies on);
- long-pool/short-request serving stays bit-identical to
  ``generate()`` with ONE decode compile — the kernel change is
  invisible at the token contract;
- ``mixed_precision="bf16"`` tracks the f32 loss trajectory within an
  asserted bound on BOTH front doors, keeps the master f32, and hands
  f32 gradients to the wire;
- remat policies are gradient-equivalent and typed-validated.
"""

import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import distributed_pytorch_tpu as dist
from distributed_pytorch_tpu import models, optim
from distributed_pytorch_tpu.models.generate import (decode_step_slots,
                                                     decode_step_slots_paged,
                                                     make_generate_fn)
from distributed_pytorch_tpu.models.transformer import (REMAT_POLICIES,
                                                        resolve_remat)
from distributed_pytorch_tpu.nn.attention import dense_attention
from distributed_pytorch_tpu.ops.decode_attention import (
    DECODE_BLOCK, blockwise_decode_attention, paged_decode_attention,
    resident_blocks)
from distributed_pytorch_tpu.ops.losses import cross_entropy
from distributed_pytorch_tpu.parallel import make_train_step, mp_cast_params
from distributed_pytorch_tpu.parallel.data_parallel import MP_POLICIES
from distributed_pytorch_tpu.serve import (EngineConfig, InferenceEngine,
                                           SamplingParams)

SCALE = 0.125  # 1/sqrt(64); tests use Dh in {8, 64} with explicit scale


def _dense_ref(hq, k, v, idx, scale):
    """The dense decode softmax the kernels replace (the exact
    pre-blockwise math of decode_step_slots)."""
    b, h, _, dh = hq.shape
    hkv = k.shape[1]
    hq_g = hq.reshape(b, hkv, h // hkv, 1, dh)
    logits = jnp.einsum("bngqd,bnkd->bngqk", hq_g, k).astype(
        jnp.float32) * scale
    mask = jnp.arange(k.shape[2])[None, :] <= idx[:, None]
    logits = jnp.where(mask[:, None, None, None, :], logits, -jnp.inf)
    probs = jax.nn.softmax(logits, axis=-1).astype(v.dtype)
    return jnp.einsum("bngqk,bnkd->bngqd", probs, v).reshape(b, h, 1, dh)


def _rand(rng, shape, dtype=jnp.float32):
    return jnp.asarray(rng.standard_normal(shape), dtype)


class TestBlockwiseKernel:
    def test_matches_dense_reference_gqa_ragged(self):
        """Contiguous cache, GQA (H=4 over Hkv=2), width NOT a block
        multiple: blockwise == dense within f32 merge tolerance."""
        rng = np.random.default_rng(0)
        b, h, hkv, w, dh, blk = 3, 4, 2, 41, 8, 16
        hq = _rand(rng, (b, h, 1, dh))
        k = _rand(rng, (b, hkv, w, dh))
        v = _rand(rng, (b, hkv, w, dh))
        idx = jnp.asarray([0, 7, 40], jnp.int32)
        scale = 1.0 / math.sqrt(dh)
        out = blockwise_decode_attention(hq, k, v, idx, scale=scale,
                                         block_len=blk)
        np.testing.assert_allclose(out, _dense_ref(hq, k, v, idx, scale),
                                   rtol=2e-6, atol=2e-6)

    def test_dead_blocks_never_touched(self):
        """NaN-poison every position past the resident blocks: one
        gathered element would poison the output, so bit-equality with
        the clean run IS the visits-only-resident-blocks claim — and
        the trip count matches ceil((max_len+1)/block)."""
        rng = np.random.default_rng(1)
        b, hkv, w, dh, blk = 2, 2, 64, 8, 16
        hq = _rand(rng, (b, 2 * hkv, 1, dh))
        k = _rand(rng, (b, hkv, w, dh))
        v = _rand(rng, (b, hkv, w, dh))
        idx = jnp.asarray([3, 21], jnp.int32)
        nb = int(resident_blocks(idx, blk, w // blk))
        assert nb == int(max(idx)) // blk + 1 == 2
        clean = blockwise_decode_attention(hq, k, v, idx, scale=SCALE,
                                           block_len=blk)
        k_p = k.at[:, :, nb * blk:, :].set(jnp.nan)
        v_p = v.at[:, :, nb * blk:, :].set(jnp.nan)
        poisoned = blockwise_decode_attention(hq, k_p, v_p, idx,
                                              scale=SCALE, block_len=blk)
        assert bool(jnp.all(jnp.isfinite(poisoned)))
        np.testing.assert_array_equal(np.asarray(clean),
                                      np.asarray(poisoned))

    def test_fully_masked_visited_block_contributes_zero(self):
        """A short row co-resident with a long one sees whole visited
        blocks fully masked; with -inf masking the online merge would
        emit NaN (exp(0)=1 ghosts or -inf - -inf). The finite-sentinel
        fix keeps the short row exactly equal to its dense softmax."""
        rng = np.random.default_rng(2)
        b, hkv, w, dh, blk = 2, 1, 48, 8, 16
        hq = _rand(rng, (b, hkv, 1, dh))
        k = _rand(rng, (b, hkv, w, dh))
        v = _rand(rng, (b, hkv, w, dh))
        idx = jnp.asarray([2, 47], jnp.int32)   # row 0: blocks 1,2 dead
        out = blockwise_decode_attention(hq, k, v, idx, scale=SCALE,
                                         block_len=blk)
        assert bool(jnp.all(jnp.isfinite(out)))
        np.testing.assert_allclose(out, _dense_ref(hq, k, v, idx, SCALE),
                                   rtol=2e-6, atol=2e-6)

    def test_paged_matches_dense_gather_incl_inactive(self):
        """Paged kernel == gather-the-whole-table dense reference, with
        the write-position re-select giving INACTIVE rows (whose pool
        scatter was dropped) their own key — decode_step_slots' exact
        value semantics."""
        rng = np.random.default_rng(3)
        b, h, hkv, dh, pl, p, n_pages = 3, 4, 2, 8, 8, 6, 13
        hq = _rand(rng, (b, h, 1, dh))
        kp = _rand(rng, (n_pages, hkv, pl, dh))
        vp = _rand(rng, (n_pages, hkv, pl, dh))
        tables = jnp.asarray(rng.integers(0, n_pages, (b, p)), jnp.int32)
        nk = _rand(rng, (b, hkv, 1, dh))
        nv = _rand(rng, (b, hkv, 1, dh))
        idx = jnp.asarray([1, 14, 39], jnp.int32)
        out = paged_decode_attention(hq, kp, vp, tables, idx, nk, nv,
                                     scale=SCALE, page_len=pl)
        # dense reference: gather the full table, re-select at idx
        g = kp[tables].transpose(0, 2, 1, 3, 4).reshape(b, hkv, p * pl, dh)
        gv = vp[tables].transpose(0, 2, 1, 3, 4).reshape(b, hkv, p * pl, dh)
        wm = (jnp.arange(p * pl)[None, :] == idx[:, None])[:, None, :, None]
        ref = _dense_ref(hq, jnp.where(wm, nk, g), jnp.where(wm, nv, gv),
                         idx, SCALE)
        np.testing.assert_allclose(out, ref, rtol=2e-6, atol=2e-6)

    def test_paged_dead_pages_never_gathered(self):
        """Pages only reachable past the resident blocks are NaN-
        poisoned; the paged scan must not read them."""
        rng = np.random.default_rng(4)
        b, hkv, dh, pl, p, n_pages = 2, 2, 8, 8, 6, 8
        hq = _rand(rng, (b, 2 * hkv, 1, dh))
        kp = _rand(rng, (n_pages, hkv, pl, dh))
        vp = _rand(rng, (n_pages, hkv, pl, dh))
        # rows use pages 0..3; pages 4.. are dead-tail table entries
        tables = jnp.asarray([[0, 1, 4, 5, 6, 7],
                              [2, 3, 4, 5, 6, 7]], jnp.int32)
        idx = jnp.asarray([5, 12], jnp.int32)   # max 12 -> 2 pages
        nk = _rand(rng, (b, hkv, 1, dh))
        nv = _rand(rng, (b, hkv, 1, dh))
        assert int(resident_blocks(idx, pl, p)) == 2
        clean = paged_decode_attention(hq, kp, vp, tables, idx, nk, nv,
                                       scale=SCALE, page_len=pl)
        kp_p = kp.at[4:].set(jnp.nan)
        vp_p = vp.at[4:].set(jnp.nan)
        poisoned = paged_decode_attention(hq, kp_p, vp_p, tables, idx,
                                          nk, nv, scale=SCALE, page_len=pl)
        assert bool(jnp.all(jnp.isfinite(poisoned)))
        np.testing.assert_array_equal(np.asarray(clean),
                                      np.asarray(poisoned))

    def test_resident_blocks_formula(self):
        assert int(resident_blocks(jnp.asarray([0], jnp.int32), 16, 8)) == 1
        assert int(resident_blocks(jnp.asarray([15], jnp.int32), 16, 8)) == 1
        assert int(resident_blocks(jnp.asarray([16], jnp.int32), 16, 8)) == 2
        # clamped at the table width however long the lengths claim
        assert int(resident_blocks(jnp.asarray([999], jnp.int32), 16, 8)) == 8


class TestF32StatsContract:
    """bf16 compute must not degrade softmax accumulation — the
    mixed-precision guard of docs/compute.md."""

    def test_dense_attention_f32_stats_under_bf16(self):
        """512 identical keys: a bf16 normalizer (8 mantissa bits)
        cannot even represent the running sum past 256 (256 + 1 == 256
        in bf16), so a bf16-stats softmax would visibly lose mass. The
        f32-stats contract keeps the result at the f32 reference."""
        s, dh = 512, 64
        q = jnp.ones((1, 1, 1, dh), jnp.bfloat16)
        k = jnp.ones((1, 1, s, dh), jnp.bfloat16)
        v = jnp.ones((1, 1, s, dh), jnp.bfloat16)
        out = dense_attention(q, k, v, causal=False)
        assert out.dtype == jnp.bfloat16
        # uniform probs over identical unit values -> exactly 1.0
        np.testing.assert_allclose(np.asarray(out, np.float32), 1.0,
                                   rtol=1e-2)
        # the probabilities themselves are formed in f32: softmax over
        # equal logits is exactly uniform, so the sum is exactly s/s
        probs = jax.nn.softmax(jnp.zeros((s,), jnp.float32))
        assert float(jnp.sum(probs)) == pytest.approx(1.0, abs=1e-6)

    def test_blockwise_f32_stats_under_bf16(self):
        s, dh, blk = 512, 64, 128
        q = jnp.ones((1, 1, 1, dh), jnp.bfloat16)
        k = jnp.ones((1, 1, s, dh), jnp.bfloat16)
        v = jnp.ones((1, 1, s, dh), jnp.bfloat16)
        out = blockwise_decode_attention(
            q, k, v, jnp.asarray([s - 1], jnp.int32),
            scale=1.0 / math.sqrt(dh), block_len=blk)
        assert out.dtype == jnp.bfloat16
        np.testing.assert_allclose(np.asarray(out, np.float32), 1.0,
                                   rtol=1e-2)

    def test_dense_fully_masked_row_nan_contract_unchanged(self):
        """Causal with s_q > s_k leaves whole rows with no visible key;
        dense softmax yields NaN there BY DESIGN and the flash kernel
        matches it — pin that the decode-path NaN fix did not leak into
        the training kernels' contract."""
        q = jnp.ones((1, 1, 3, 8))
        k = jnp.ones((1, 1, 1, 8))
        out = dense_attention(q, k, k, causal=True)
        # rows 0,1 sit above the shifted diagonal (off = 1-3 = -2)
        assert bool(jnp.all(jnp.isnan(out[0, 0, 0])))
        assert bool(jnp.all(jnp.isfinite(out[0, 0, 2])))


class TestDecodePathIntegration:
    def test_decode_step_slots_blockwise_equals_dense_path(self):
        """The kernel swap is invisible at the decode-step contract:
        same written caches (bit-exact) and logits within f32 merge
        tolerance of the dense path."""
        model = models.TransformerLM(vocab=61, dim=32, n_layers=2,
                                     n_heads=4, n_kv_heads=2, pos="rope",
                                     max_seq=512)
        params = model.init(jax.random.PRNGKey(0))
        b, w = 3, 320    # 3 DECODE_BLOCK-sized blocks when blk=128
        dh = model.dim // model.n_heads
        rng = np.random.default_rng(5)
        ks = [_rand(rng, (b, 2, w, dh)) for _ in range(2)]
        vs = [_rand(rng, (b, 2, w, dh)) for _ in range(2)]
        lengths = jnp.asarray([0, 130, 300], jnp.int32)
        tokens = jnp.asarray([1, 2, 3], jnp.int32)
        lo_b, ks_b, vs_b = decode_step_slots(model, params, ks, vs,
                                             lengths, tokens)
        lo_d, ks_d, vs_d = decode_step_slots(model, params, ks, vs,
                                             lengths, tokens,
                                             blockwise=False)
        # layer 0's written K/V precede any attention, so they are
        # bit-identical; deeper layers' writes inherit the f32 merge-
        # order difference of the previous layer's attention output
        np.testing.assert_array_equal(np.asarray(ks_b[0]),
                                      np.asarray(ks_d[0]))
        np.testing.assert_array_equal(np.asarray(vs_b[0]),
                                      np.asarray(vs_d[0]))
        for a, c in zip(ks_b[1:] + vs_b[1:], ks_d[1:] + vs_d[1:]):
            np.testing.assert_allclose(np.asarray(a), np.asarray(c),
                                       rtol=2e-5, atol=2e-5)
        np.testing.assert_allclose(lo_b, lo_d, rtol=2e-5, atol=2e-5)

    def test_long_pool_short_requests_bit_identical_one_compile(self):
        """A slot pool sized for 320-position requests serving short
        ones: token streams bit-identical to generate(), ONE decode
        compile — the O(resident) kernel is invisible at the serving
        contract."""
        model = models.TransformerLM(vocab=61, dim=32, n_layers=1,
                                     n_heads=4, n_kv_heads=2, pos="rope",
                                     max_seq=512)
        params = model.init(jax.random.PRNGKey(0))
        rng = np.random.default_rng(6)
        prompts = [rng.integers(0, 61, (s,)).astype(np.int32)
                   for s in (3, 7, 5)]
        sp = SamplingParams(max_new_tokens=6)
        keys = [jax.random.PRNGKey(10 + i) for i in range(3)]
        eng = InferenceEngine(model, params,
                              EngineConfig(n_slots=3, max_len=320))
        with eng:
            outs = [eng.submit(p, sp, rng=k).result(timeout=120)
                    for p, k in zip(prompts, keys)]
        assert eng.pool.compiles.decode == 1
        # retirement releases the slot LENGTH too (SlotPool.release):
        # a frozen long length would keep max(lengths) — the blockwise
        # trip count — paying for requests that no longer exist
        assert int(jnp.max(eng.pool.lengths)) == 0
        for p, k, out in zip(prompts, keys, outs):
            fn = make_generate_fn(model, sp.max_new_tokens, max_len=320)
            ref = np.asarray(jax.jit(fn)(params, jnp.asarray(p[None]),
                                         k))[0]
            np.testing.assert_array_equal(out, ref)

    def test_paged_long_pool_short_requests_one_compile(self):
        """Paged engine whose tables span 16 pages/slot serving ~2-page
        requests: streams == generate(), ONE paged decode compile."""
        model = models.TransformerLM(vocab=61, dim=32, n_layers=1,
                                     n_heads=4, n_kv_heads=2, pos="rope",
                                     max_seq=256)
        params = model.init(jax.random.PRNGKey(0))
        rng = np.random.default_rng(7)
        prompts = [rng.integers(0, 61, (s,)).astype(np.int32)
                   for s in (5, 9)]
        sp = SamplingParams(max_new_tokens=5)
        keys = [jax.random.PRNGKey(20 + i) for i in range(2)]
        eng = InferenceEngine(model, params,
                              EngineConfig(n_slots=2, max_len=128,
                                           paged=True, page_len=8))
        with eng:
            outs = [eng.submit(p, sp, rng=k).result(timeout=120)
                    for p, k in zip(prompts, keys)]
        assert eng.pool.compiles.decode == 1
        for p, k, out in zip(prompts, keys, outs):
            fn = make_generate_fn(model, sp.max_new_tokens, max_len=128)
            ref = np.asarray(jax.jit(fn)(params, jnp.asarray(p[None]),
                                         k))[0]
            np.testing.assert_array_equal(out, ref)

    def test_paged_decode_visits_only_resident_pages(self):
        """The synthetic long-pool/short-request case at the decode-op
        level: NaN-poison every pool page the two requests don't own;
        decode_step_slots_paged must produce finite logits identical to
        the clean pool — the scan visited only ceil(len/page_len)
        blocks of each table."""
        model = models.TransformerLM(vocab=61, dim=32, n_layers=1,
                                     n_heads=4, n_kv_heads=2, pos="rope",
                                     max_seq=256)
        params = model.init(jax.random.PRNGKey(0))
        rng = np.random.default_rng(8)
        pl, n_pages, p_per = 8, 32, 12
        dh = model.dim // model.n_heads
        kp = [_rand(rng, (n_pages, 2, pl, dh))]
        vp = [_rand(rng, (n_pages, 2, pl, dh))]
        # slot 0 owns pages 0,1; slot 1 owns 2,3 — tails point at junk
        tables = jnp.asarray(
            [[0, 1] + list(range(10, 20)),
             [2, 3] + list(range(20, 30))], jnp.int32)
        lengths = jnp.asarray([9, 14], jnp.int32)   # 2 pages resident
        tokens = jnp.asarray([1, 2], jnp.int32)
        active = jnp.asarray([True, True])
        nb = int(resident_blocks(lengths, pl, p_per))
        assert nb == 2 == math.ceil((int(max(lengths)) + 1) / pl)
        lo, _, _ = decode_step_slots_paged(model, params, kp, vp, tables,
                                           lengths, tokens, active,
                                           page_len=pl)
        poisoned_k = [kp[0].at[4:].set(jnp.nan)]
        poisoned_v = [vp[0].at[4:].set(jnp.nan)]
        lo_p, _, _ = decode_step_slots_paged(model, params, poisoned_k,
                                             poisoned_v, tables, lengths,
                                             tokens, active, page_len=pl)
        assert bool(jnp.all(jnp.isfinite(lo_p)))
        np.testing.assert_array_equal(np.asarray(lo), np.asarray(lo_p))


# ---------------------------------------------------------------------------
# mixed precision
# ---------------------------------------------------------------------------


def _lm_loss(model):
    def loss_fn(p, toks):
        logits = model.apply(p, toks[:, :-1]).astype(jnp.float32)
        return cross_entropy(logits, toks[:, 1:]), {}
    return loss_fn


def _mp_trajectories(mp, *, world=1, steps=8, backend=None):
    if world > 1 or backend:
        dist.init_process_group(0, world, backend=backend)
    try:
        model = models.TransformerLM(vocab=64, dim=32, n_layers=2,
                                     n_heads=2, max_seq=32)
        params = model.init(jax.random.PRNGKey(0))
        opt = optim.adamw(1e-2)
        step = make_train_step(_lm_loss(model), opt, donate=False,
                               mixed_precision=mp)
        toks = np.asarray(jax.random.randint(
            jax.random.PRNGKey(1), (4 * max(world, 1), 17), 0, 64,
            dtype=jnp.int32))
        batch = dist.shard_batch(toks) if world > 1 else jnp.asarray(toks)
        p, st = params, opt.init(params)
        losses = []
        for _ in range(steps):
            out = step(p, st, batch)
            p, st = out.params, out.opt_state
            losses.append(float(np.asarray(out.loss).mean()))
        return losses, p
    finally:
        if world > 1 or backend:
            dist.cleanup()


class TestMixedPrecision:
    def test_bf16_tracks_f32_spmd_front_door(self):
        """The asserted loss-trajectory bound, mesh front door (world
        4): bf16 compute with the f32 master stays within 2% relative
        of the f32 step at every one of 8 steps."""
        f32, _ = _mp_trajectories("off", world=4)
        bf16, p = _mp_trajectories("bf16", world=4)
        rel = np.abs(np.array(f32) - np.array(bf16)) / np.abs(f32)
        assert rel.max() < 0.02, (f32, bf16)
        # the master the optimizer updates stays f32
        assert all(l.dtype == jnp.float32
                   for l in jax.tree_util.tree_leaves(p)
                   if jnp.issubdtype(l.dtype, jnp.floating))

    def test_bf16_tracks_f32_host_front_door(self, monkeypatch):
        """Same bound through the host front door (native process
        group, world 1 — the numpy flat-bucket step path)."""
        from distributed_pytorch_tpu.runtime.launcher import find_free_port
        monkeypatch.setenv("DPX_MASTER_PORT", str(find_free_port()))
        f32, _ = _mp_trajectories("off", backend="host")
        monkeypatch.setenv("DPX_MASTER_PORT", str(find_free_port()))
        bf16, _ = _mp_trajectories("bf16", backend="host")
        rel = np.abs(np.array(f32) - np.array(bf16)) / np.abs(f32)
        assert rel.max() < 0.02, (f32, bf16)

    def test_gradients_reach_the_wire_in_f32(self):
        """The cast is linear, so grads come back in the MASTER's dtype
        — the quantized wire and the sharded update see f32 trees."""
        model = models.TransformerLM(vocab=32, dim=16, n_layers=1,
                                     n_heads=2, max_seq=16)
        params = model.init(jax.random.PRNGKey(0))
        toks = jax.random.randint(jax.random.PRNGKey(1), (2, 9), 0, 32,
                                  dtype=jnp.int32)
        loss_fn = _lm_loss(model)

        def mp_loss(p, b):
            return loss_fn(mp_cast_params(p), b)

        grads = jax.grad(lambda p: mp_loss(p, toks)[0])(params)
        assert all(g.dtype == jnp.float32
                   for g in jax.tree_util.tree_leaves(grads))

    def test_mp_cast_rule(self):
        tree = {"w": jnp.ones((2,), jnp.float32),
                "i": jnp.ones((2,), jnp.int32),
                "b": jnp.ones((2,), jnp.bfloat16)}
        out = mp_cast_params(tree)
        assert out["w"].dtype == jnp.bfloat16
        assert out["i"].dtype == jnp.int32
        assert out["b"].dtype == jnp.bfloat16

    def test_typed_rejection_and_env_default(self, monkeypatch):
        model = models.DummyModel(in_dim=1, hidden_dim=4, n_classes=2)

        def loss_fn(p, b):
            return jnp.float32(0.0), {}

        with pytest.raises(ValueError, match="mixed_precision"):
            make_train_step(loss_fn, optim.adamw(1e-3),
                            mixed_precision="fp8")
        assert set(MP_POLICIES) == {"off", "bf16"}
        # env default: DPX_MP_POLICY drives the None case (typed knob)
        monkeypatch.setenv("DPX_MP_POLICY", "bogus")
        with pytest.raises(ValueError, match="mixed_precision"):
            make_train_step(loss_fn, optim.adamw(1e-3))
        monkeypatch.setenv("DPX_MP_POLICY", "bf16")
        make_train_step(loss_fn, optim.adamw(1e-3))   # resolves + wraps


# ---------------------------------------------------------------------------
# remat policies
# ---------------------------------------------------------------------------


class TestRematPolicies:
    def test_gradient_equivalence_across_policies(self):
        """Remat changes WHEN activations exist, never the math: every
        policy's gradients match the no-remat gradients."""
        toks = jax.random.randint(jax.random.PRNGKey(1), (2, 17), 0, 64,
                                  dtype=jnp.int32)
        flat = {}
        for pol in REMAT_POLICIES:
            model = models.TransformerLM(vocab=64, dim=32, n_layers=2,
                                         n_heads=2, max_seq=32, remat=pol)
            params = model.init(jax.random.PRNGKey(0))
            g = jax.grad(lambda p: cross_entropy(
                model.apply(p, toks[:, :-1]).astype(jnp.float32),
                toks[:, 1:]))(params)
            flat[pol] = np.concatenate(
                [np.ravel(l) for l in jax.tree_util.tree_leaves(g)])
        for pol in ("full", "dots_saveable"):
            np.testing.assert_allclose(flat[pol], flat["none"],
                                       rtol=1e-5, atol=1e-6)

    def test_resolution_bools_env_and_rejection(self, monkeypatch):
        assert resolve_remat(False) == "none"
        assert resolve_remat(True) == "full"
        assert resolve_remat("dots_saveable") == "dots_saveable"
        monkeypatch.setenv("DPX_REMAT", "full")
        assert resolve_remat(None) == "full"
        monkeypatch.delenv("DPX_REMAT")
        assert resolve_remat(None) == "none"
        with pytest.raises(ValueError, match="remat"):
            resolve_remat("everything")
        m = models.TransformerLM(vocab=8, dim=8, n_layers=1, n_heads=1,
                                 max_seq=8, remat="full")
        assert m.remat is True and m.remat_policy == "full"


# ---------------------------------------------------------------------------
# flash crossover knob
# ---------------------------------------------------------------------------


class TestFlashMinSeqKnob:
    def test_env_drives_dispatch(self, monkeypatch):
        """DPX_FLASH_MIN_SEQ is read at attn_fn BUILD time: above the
        threshold the pallas kernel runs, below it the dense einsum —
        observed by making the kernel path unmistakable."""
        # the module, not the same-named function ops/__init__ re-exports
        # (import ... as would resolve the package ATTRIBUTE, which the
        # __init__ from-import shadowed with the function)
        import importlib
        fa = importlib.import_module(
            "distributed_pytorch_tpu.ops.flash_attention")

        calls = []
        real = fa.flash_attention

        def spy(*a, **kw):
            calls.append(1)
            return real(*a, **kw)

        monkeypatch.setattr(fa, "flash_attention", spy)
        q = jnp.asarray(np.random.default_rng(0).standard_normal(
            (1, 2, 32, 8)), jnp.float32)
        monkeypatch.setenv("DPX_FLASH_MIN_SEQ", "64")
        fa.make_flash_attn_fn()(q, q, q, causal=True)
        assert not calls                       # 32 < 64 -> dense
        monkeypatch.setenv("DPX_FLASH_MIN_SEQ", "16")
        fa.make_flash_attn_fn()(q, q, q, causal=True)
        assert calls                           # 32 >= 16 -> kernel
