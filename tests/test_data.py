"""Sharded sampler + loader contracts (reference DistributedSampler
behavior: rank-striding, wrap padding, set_epoch reshuffle — SURVEY.md §2.3
row 6; loader layout invariant from data/loader.py)."""

import numpy as np
import pytest

import distributed_pytorch_tpu as dist
from distributed_pytorch_tpu.data import (DataLoader, DummyDataset,
                                          ShardedSampler, data_sampler)


def test_data_sampler_none_when_not_distributed():
    ds = DummyDataset(32, 4)
    assert data_sampler(ds, distributed=False, shuffle=False) is None


def test_shards_are_disjoint_and_cover():
    s = [ShardedSampler(32, rank=r, world_size=4, shuffle=False)
         for r in range(4)]
    locals_ = [set(x.local_indices().tolist()) for x in s]
    assert all(len(a) == 8 for a in locals_)
    union = set().union(*locals_)
    assert union == set(range(32))
    for i in range(4):
        for j in range(i + 1, 4):
            assert locals_[i].isdisjoint(locals_[j])


def test_rank_striding_matches_torch_sampler_contract():
    s = ShardedSampler(16, rank=1, world_size=4, shuffle=False)
    np.testing.assert_array_equal(s.local_indices(), [1, 5, 9, 13])


def test_padding_wraps_to_equal_shards():
    # 10 samples over 4 ranks -> ceil = 3 each, padded from the front
    samplers = [ShardedSampler(10, rank=r, world_size=4, shuffle=False)
                for r in range(4)]
    assert all(len(s) == 3 for s in samplers)
    all_idx = np.concatenate([s.local_indices() for s in samplers])
    assert sorted(all_idx.tolist()) == sorted(
        list(range(10)) + [0, 1])  # wrap-pad repeats the start


def test_set_epoch_reshuffles_consistently():
    a = ShardedSampler(32, rank=0, world_size=4, shuffle=True, seed=7)
    b = ShardedSampler(32, rank=2, world_size=4, shuffle=True, seed=7)
    a.set_epoch(1)
    b.set_epoch(1)
    # same epoch -> same global permutation on every rank
    np.testing.assert_array_equal(a.global_indices(), b.global_indices())
    e1 = a.global_indices().copy()
    a.set_epoch(2)
    assert not np.array_equal(e1, a.global_indices())


def test_shuffle_false_is_arange_order():
    s = ShardedSampler(8, rank=0, world_size=2, shuffle=True)
    t = ShardedSampler(8, rank=0, world_size=2, shuffle=False)
    np.testing.assert_array_equal(t.global_indices(), np.arange(8))
    assert not np.array_equal(s.global_indices(), t.global_indices())


def test_loader_global_batch_layout(group8):
    """Step t's global batch rows [r*B:(r+1)*B] must equal what rank r's
    per-process loader would have produced (the layout invariant the DP
    engine relies on)."""
    ds = DummyDataset(32, 4)
    sampler = data_sampler(ds, distributed=True, shuffle=False)
    loader = DataLoader(ds, batch_size=2, sampler=sampler)
    batches = list(loader)
    assert len(loader) == len(batches) == 2  # 32/(8 ranks)/2 per rank
    x0, y0 = batches[0]
    assert x0.shape == (16, 1)
    for r in range(8):
        # rank r, strided shard: indices r, r+8, ... ; first batch = first 2
        np.testing.assert_array_equal(
            x0[2 * r: 2 * r + 2, 0], [r, r + 8])


def test_loader_non_distributed_shuffles():
    ds = DummyDataset(32, 4)
    loader = DataLoader(ds, batch_size=8, sampler=None, shuffle=True)
    xs = np.concatenate([b[0] for b in loader])
    assert xs.shape == (32, 1)
    assert not np.array_equal(xs[:, 0], np.arange(32))  # shuffled
    assert sorted(xs[:, 0].tolist()) == list(range(32))


def test_dummy_dataset_deterministic():
    a, b = DummyDataset(32, 4), DummyDataset(32, 4)
    np.testing.assert_array_equal(a.labels, b.labels)
    np.testing.assert_array_equal(a.data[:, 0], np.arange(32))
