"""DP engine correctness: the loss-parity integration test of SURVEY.md §4 —
N-device training must reproduce the single-device loss trajectory exactly
(same global batch), operationalizing BASELINE.json's 'loss-curve parity'.
Also checks prepare_ddp_model's wrap-iff-distributed contract
(reference distributed.py:112-115)."""

import importlib

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import distributed_pytorch_tpu as dist
from distributed_pytorch_tpu import models, optim
from distributed_pytorch_tpu.ops.losses import cross_entropy_per_example
from distributed_pytorch_tpu.parallel import (DataParallel, make_train_step,
                                              prepare_ddp_model)


def _loss_fn(model):
    def loss_fn(p, batch):
        x, y = batch
        logits = model.apply(p, x)
        per_ex = cross_entropy_per_example(logits, y)
        return per_ex.mean(), {"correct": jnp.argmax(logits, -1) == y}
    return loss_fn


def _run(world_size, steps=8, global_batch=32):
    """Train DummyModel on a fixed global batch stream; return losses."""
    if world_size > 1:
        dist.init_process_group(0, world_size)
    model = models.DummyModel(in_dim=1, hidden_dim=16, n_classes=4)
    params = dist.replicate(model.init(jax.random.PRNGKey(0)))
    optimizer = optim.adamw(1e-3)
    opt_state = dist.replicate(optimizer.init(params))
    step = make_train_step(_loss_fn(model), optimizer)

    rng = np.random.default_rng(0)
    losses = []
    for t in range(steps):
        x = rng.random((global_batch, 1), dtype=np.float32)
        y = rng.integers(0, 4, size=(global_batch,)).astype(np.int32)
        batch = dist.shard_batch((x, y))
        params, opt_state, loss, metrics = step(params, opt_state, batch)
        # global mean loss = mean of per-rank means (equal shards)
        losses.append(float(np.asarray(loss).mean()))
    dist.cleanup()
    return losses


def test_loss_parity_1_vs_8_devices():
    """Same global batches, 1 vs 8 devices: identical trajectories."""
    ref = _run(world_size=1)
    dpp = _run(world_size=8)
    np.testing.assert_allclose(ref, dpp, rtol=2e-5, atol=2e-6)


def test_loss_decreases():
    losses = _run(world_size=8, steps=16)
    assert losses[-1] < losses[0]


def test_per_rank_losses_stacked_layout(group8):
    model = models.DummyModel(in_dim=1, hidden_dim=8, n_classes=4)
    params = dist.replicate(model.init(jax.random.PRNGKey(0)))
    optimizer = optim.sgd(0.1)
    opt_state = dist.replicate(optimizer.init(params))
    step = make_train_step(_loss_fn(model), optimizer)
    x = np.arange(16, dtype=np.float32)[:, None]
    y = np.zeros((16,), dtype=np.int32)
    out = step(params, opt_state, dist.shard_batch((x, y)))
    assert out.loss.shape == (8,)
    assert np.asarray(out.metrics["correct"]).shape == (16,)
    # stacked per-rank losses feed the eager collectives directly
    total = dist.reduce(out.loss)
    np.testing.assert_allclose(float(total), float(np.asarray(out.loss).sum()),
                               rtol=1e-6)


def test_grad_sync_keeps_params_replicated(group8):
    """After a step, every device's param copy must be identical — DDP's
    invariant (ctor broadcast + synchronized updates)."""
    model = models.DummyModel(in_dim=1, hidden_dim=8, n_classes=4)
    params = dist.replicate(model.init(jax.random.PRNGKey(0)))
    optimizer = optim.adamw(1e-2)
    opt_state = dist.replicate(optimizer.init(params))
    step = make_train_step(_loss_fn(model), optimizer)
    rng = np.random.default_rng(1)
    x = rng.random((16, 1), dtype=np.float32)
    y = rng.integers(0, 4, size=(16,)).astype(np.int32)
    params, _, _, _ = step(params, opt_state, dist.shard_batch((x, y)))
    w = params["lin1"]["w"]
    shards = [np.asarray(s.data) for s in w.addressable_shards]
    for s in shards[1:]:
        np.testing.assert_array_equal(shards[0], s)


def test_prepare_ddp_model_identity_world1():
    model = models.DummyModel()
    assert prepare_ddp_model(model, device_ids=[0]) is model


def test_prepare_ddp_model_wraps_when_distributed(group8):
    model = models.DummyModel()
    params = model.init(jax.random.PRNGKey(0))
    wrapped = prepare_ddp_model(model, device_ids=[0], params=params)
    assert isinstance(wrapped, DataParallel)
    x = jnp.ones((8, 1))
    out = wrapped(wrapped.params, x)
    assert out.shape == (8, 4)


def test_example_min_ddp_parity_0_1_8_devices(monkeypatch, capsys):
    """The workload runs unmodified on 0, 1, and 8 devices with identical
    loss trajectories (graceful degradation + loss parity end to end).
    World 0/1 use global batch 8 (= the default per-rank batch); the 8-rank
    run uses per-rank batch 1 for the same global batch."""
    import examples.min_ddp as example

    histories = {}
    for world, argv in [
        (0, ["--epochs", "2", "--batch-size", "8"]),
        (1, ["--epochs", "2", "--batch-size", "8"]),
        (8, ["--epochs", "2", "--batch-size", "1"]),
    ]:
        hist = []
        monkeypatch.setenv("DPX_CPU_DEVICES", str(max(world, 1)) if world else "")
        if world == 0:
            monkeypatch.delenv("DPX_CPU_DEVICES", raising=False)
        example.main_worker(0, world, argv=argv, quiet=True, history=hist)
        histories[world] = hist

    assert len(histories[0]) == len(histories[1]) == len(histories[8]) == 8
    np.testing.assert_allclose(histories[0], histories[1], rtol=1e-6)

    # The single-process run shuffles while the distributed one doesn't
    # (reference quirk, min_DDP.py:64-66), so for stepwise parity compare
    # the 8-rank run against an *unshuffled* single-device run: same global
    # batches in the same order.
    orig_loader = example.DataLoader

    def no_shuffle_loader(*a, **kw):
        kw["shuffle"] = False
        return orig_loader(*a, **kw)

    monkeypatch.setattr(example, "DataLoader", no_shuffle_loader)
    monkeypatch.setenv("DPX_CPU_DEVICES", "1")
    ref_ns = []
    example.main_worker(0, 1, argv=["--epochs", "2", "--batch-size", "8"],
                        quiet=True, history=ref_ns)
    # 8-rank reduce is SUM of per-rank mean losses (the reference's
    # sum-not-avg quirk); per-rank batch 1 makes that 8x the global mean.
    dpp = [v / 8.0 for v in histories[8]]
    np.testing.assert_allclose(ref_ns, dpp, rtol=2e-4, atol=1e-5)


def test_int8_grad_reduce_trains(group8):
    """grad_reduce='int8': the compressed all-reduce trains the
    reference workload to a decreasing loss, tracking the exact-reduce
    step closely (quantization error is far below SGD scale)."""
    from distributed_pytorch_tpu.ops.losses import cross_entropy

    model = models.DummyModel(in_dim=1, hidden_dim=32, n_classes=4)
    params = model.init(jax.random.PRNGKey(0))
    opt = optim.adamw(1e-3)

    def loss_fn(p, batch):
        x, y = batch
        return cross_entropy(model.apply(p, x), y), {}

    x = dist.shard_batch(np.arange(16, dtype=np.float32)[:, None])
    y = dist.shard_batch((np.arange(16) % 4).astype(np.int32))

    with pytest.raises(ValueError, match="grad_reduce"):
        make_train_step(loss_fn, opt, grad_reduce="fp4")

    step_q = make_train_step(loss_fn, opt, donate=False,
                             grad_reduce="int8")
    step_e = make_train_step(loss_fn, opt, donate=False)
    pq, pe = params, params
    sq, se = opt.init(params), opt.init(params)
    losses_q, losses_e = [], []
    for _ in range(6):
        oq = step_q(pq, sq, (x, y))
        ox = step_e(pe, se, (x, y))
        pq, sq = oq.params, oq.opt_state
        pe, se = ox.params, ox.opt_state
        losses_q.append(float(oq.loss.mean()))
        losses_e.append(float(ox.loss.mean()))
    assert losses_q[-1] < losses_q[0]
    np.testing.assert_allclose(losses_q, losses_e, rtol=5e-3, atol=5e-3)
