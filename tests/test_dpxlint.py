"""dpxlint self-tests: every rule on good/bad fixtures, the inline
allowlist, the baseline mechanism, the repo-clean gate, and the
generated-docs freshness check (ISSUE 5)."""

import json
import os
import textwrap

import pytest

from distributed_pytorch_tpu.analysis import lint
from distributed_pytorch_tpu.analysis.schedule import (
    check_front_door_parity, extract_schedules)


def _lint_snippet(tmp_path, source, rel="distributed_pytorch_tpu/mod.py"):
    """Lint one fixture file at a package-relative path (DPX003 is
    package-scoped; DPX002 exempts tests/)."""
    path = tmp_path / rel
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(textwrap.dedent(source))
    return lint.lint_paths([str(path)], root=str(tmp_path))


def _rules(findings):
    return [f.rule for f in findings]


class TestRules:
    def test_dpx001_collective_on_thread_target(self, tmp_path):
        bad = """
            import threading

            def worker():
                barrier()

            t = threading.Thread(target=worker, name="w")
        """
        assert "DPX001" in _rules(_lint_snippet(tmp_path, bad))

    def test_dpx001_transitive_and_method_target(self, tmp_path):
        bad = """
            import threading

            class M:
                def _io(self):
                    self._helper()

                def _helper(self):
                    self._barrier()

                def go(self):
                    t = threading.Thread(target=self._io, name="io")
        """
        assert "DPX001" in _rules(_lint_snippet(tmp_path, bad))

    def test_dpx001_clean_thread_ok(self, tmp_path):
        good = """
            import threading

            def worker():
                return 2 + 2

            t = threading.Thread(target=worker, name="w")
        """
        assert _lint_snippet(tmp_path, good) == []

    def test_dpx002_raw_environ_and_getenv(self, tmp_path):
        bad = """
            import os
            A = os.environ.get("DPX_FOO")
            B = os.getenv("DPX_BAR")
            os.environ["DPX_BAZ"] = "1"
        """
        assert _rules(_lint_snippet(tmp_path, bad)).count("DPX002") == 3

    def test_dpx002_aliased_spellings(self, tmp_path):
        """`from os import environ` / `import os as _os` / renamed
        getenv are the same raw access — every spelling is flagged."""
        bad = """
            import os as _os
            from os import environ
            from os import getenv as _ge
            A = environ.get("DPX_A")
            B = _os.environ["DPX_B"]
            C = _ge("DPX_C")
        """
        assert _rules(_lint_snippet(tmp_path, bad)).count("DPX002") == 3

    def test_dpx002_registry_file_and_tests_exempt(self, tmp_path):
        src = """
            import os
            A = os.environ.get("DPX_FOO")
        """
        assert _lint_snippet(
            tmp_path, src,
            rel="distributed_pytorch_tpu/runtime/env.py") == []
        assert _lint_snippet(tmp_path, src, rel="tests/test_x.py") == []

    def test_dpx003_blocking_without_timeout(self, tmp_path):
        bad = """
            import subprocess

            def f(q, t, p):
                q.get()
                t.join()
                subprocess.run(["x"])
        """
        assert _rules(_lint_snippet(tmp_path, bad)).count("DPX003") == 3

    def test_dpx003_timeout_and_self_calls_ok(self, tmp_path):
        good = """
            import subprocess

            class A:
                def f(self, q, t):
                    q.get(timeout=1.0)
                    t.join(5)
                    subprocess.run(["x"], timeout=60)
                    self.wait()
        """
        assert _lint_snippet(tmp_path, good) == []

    def test_dpx003_scoped_to_package(self, tmp_path):
        src = """
            def f(q):
                q.get()
        """
        assert _lint_snippet(tmp_path, src, rel="benchmarks/b.py") == []

    def test_dpx004_unattributed_typed_raise(self, tmp_path):
        bad = """
            def f():
                raise CommTimeout("deadline")
        """
        good = """
            def f():
                raise CommTimeout("deadline", op="allreduce", rank=3)
        """
        assert "DPX004" in _rules(_lint_snippet(tmp_path, bad))
        assert _lint_snippet(tmp_path, good) == []

    def test_dpx005_unnamed_thread(self, tmp_path):
        bad = """
            import threading
            t = threading.Thread(target=print)
        """
        good = """
            import threading
            t = threading.Thread(target=print, name="printer")
        """
        findings = _lint_snippet(tmp_path, bad)
        assert "DPX005" in _rules(findings)
        assert _lint_snippet(tmp_path, good) == []

    def test_dpx006_jit_in_step_builder_without_donation(self, tmp_path):
        bad = """
            import jax

            def make_train_step(loss_fn):
                return jax.jit(loss_fn)
        """
        good = """
            import jax

            def make_train_step(loss_fn):
                return jax.jit(loss_fn, donate_argnums=(0, 1))
        """
        assert "DPX006" in _rules(_lint_snippet(tmp_path, bad))
        assert _lint_snippet(tmp_path, good) == []

    def test_dpx006_innermost_owner_and_decode(self, tmp_path):
        """Attribution is to the INNERMOST enclosing def: a sampler
        closure inside a decode builder is not a builder site, while a
        jit directly in a decode fn is."""
        mixed = """
            import jax

            def build_decode(model):
                def sampler(logits):
                    pass
                fn = jax.jit(sampler)          # in build_decode: flagged

                def make_sampler():
                    return jax.jit(sampler)    # innermost not step/decode

                return fn
        """
        findings = _lint_snippet(tmp_path, mixed)
        assert _rules(findings) == ["DPX006"]
        assert findings[0].line_text.startswith("fn = jax.jit")

    def test_dpx006_decorator_and_partial_spellings(self, tmp_path):
        """The donation lint covers every jit spelling: a bare
        @jax.jit decorator on a step/decode-named def (can never
        donate), a @jit(...) decorator without donate_argnums, and
        partial(jax.jit, ...) inside a builder."""
        bad = """
            import functools

            import jax

            @jax.jit
            def train_step(params, opt_state, batch):
                pass

            @jax.jit(static_argnums=(0,))
            def decode_step(params, cache):
                pass

            def make_train_step(loss_fn):
                return functools.partial(jax.jit,
                                         static_argnums=(0,))(loss_fn)
        """
        assert _rules(_lint_snippet(tmp_path, bad)) == ["DPX006"] * 3
        good = """
            import functools

            import jax

            @jax.jit(donate_argnums=(0, 1))
            def train_step(params, opt_state, batch):
                pass

            def make_train_step(loss_fn):
                return functools.partial(
                    jax.jit, donate_argnums=(0, 1))(loss_fn)

            @jax.jit
            def sample_logits(logits):
                pass
        """
        assert _lint_snippet(tmp_path, good) == []

    def test_dpx006_scoped_to_package_and_waivable(self, tmp_path):
        outside = """
            import jax

            def make_train_step(loss_fn):
                return jax.jit(loss_fn)
        """
        assert _lint_snippet(tmp_path, outside,
                             rel="benchmarks/mod.py") == []
        waived = """
            import jax

            def make_eval_step(fn):
                # dpxlint: disable=DPX006 eval does not own the params
                return jax.jit(fn)
        """
        assert _lint_snippet(tmp_path, waived) == []


    def test_dpx007_time_time_duration_pattern(self, tmp_path):
        bad = """
            import time

            def f():
                t0 = time.time()
                work()
                return time.time() - t0
        """
        rules = _rules(_lint_snippet(tmp_path, bad))
        # both the direct-call subtraction and the tainted-name operand
        # are the same BinOp — one finding
        assert rules == ["DPX007"]

    def test_dpx007_attribute_taint_across_methods(self, tmp_path):
        bad = """
            import time

            class Monitor:
                def __init__(self):
                    self.start_time = time.time()

                def elapsed(self):
                    now = time.time()
                    return now - self.start_time
        """
        assert "DPX007" in _rules(_lint_snippet(tmp_path, bad))

    def test_dpx007_aliased_from_import(self, tmp_path):
        bad = """
            from time import time as now

            def f():
                t0 = now()
                return now() - t0
        """
        assert "DPX007" in _rules(_lint_snippet(tmp_path, bad))

    def test_dpx007_perf_counter_and_plain_wall_ok(self, tmp_path):
        good = """
            import time

            STAMP = time.time()   # a single wall stamp: not a duration

            def f():
                t0 = time.perf_counter()
                work()
                dt = time.perf_counter() - t0
                ns = time.perf_counter_ns() - 5
                return dt, ns, time.time()
        """
        assert _lint_snippet(tmp_path, good) == []

    def test_dpx007_no_cross_function_taint_leak(self, tmp_path):
        # one function's (waived) wall-clock name must NOT taint a
        # sibling function's perf_counter duration math through the
        # module-level pass — the baseline-ZERO gate lives on no
        # false positives
        good = """
            import time

            def wall_site(last):
                start = time.time()
                # dpxlint: disable=DPX007 cross-process comparison
                return start - last

            def timed():
                start = time.perf_counter()
                end = time.perf_counter()
                return end - start
        """
        assert _lint_snippet(tmp_path, good) == []

    def test_dpx007_scoped_to_package_and_waivable(self, tmp_path):
        outside = """
            import time

            def f():
                t0 = time.time()
                return time.time() - t0
        """
        assert _lint_snippet(tmp_path, outside,
                             rel="benchmarks/mod.py") == []
        waived = """
            import time

            def staleness(last_beat):
                now = time.time()
                # dpxlint: disable=DPX007 cross-process wall comparison
                return now - last_beat
        """
        assert _lint_snippet(tmp_path, waived) == []

    def test_dpx008_unknown_event_name_flagged(self, tmp_path):
        bad = """
            from ..utils.logging import append_event

            def report():
                append_event("totaly_unknwon_event", rank=0)
        """
        found = _lint_snippet(tmp_path, bad)
        assert _rules(found) == ["DPX008"]
        assert "totaly_unknwon_event" in found[0].message

    def test_dpx008_known_names_variables_and_methods_ok(self, tmp_path):
        good = """
            from ..utils.logging import append_event

            def report(name):
                append_event("worker_failure", rank=0)
                append_event("metrics_snapshot", rank=0)
                append_event(name, rank=0)      # caller's literal is
                                                # the checked site
                logger.event("whatever_stream", rank=0)  # not append_event
        """
        assert _lint_snippet(tmp_path, good) == []

    def test_dpx008_waivable_and_tests_exempt(self, tmp_path):
        waived = """
            from ..utils.logging import append_event

            def report():
                # dpxlint: disable=DPX008 deliberately-foreign stream
                append_event("external_system_event", rank=0)
        """
        assert _lint_snippet(tmp_path, waived) == []
        in_tests = """
            def stage():
                append_event("unknown_on_purpose")
        """
        assert _lint_snippet(tmp_path, in_tests,
                             rel="tests/test_mod.py") == []

    def test_dpx008_vocabulary_is_the_export_registry(self):
        # the rule reads KNOWN_EVENTS itself — a name registered in
        # obs/export.py can never be flagged, by construction
        from distributed_pytorch_tpu.obs.export import KNOWN_EVENTS
        assert lint.KNOWN_EVENTS is KNOWN_EVENTS
        assert "metrics_snapshot" in lint.KNOWN_EVENTS
        assert "health_transition" in lint.KNOWN_EVENTS


class TestAllowlist:
    def test_inline_disable_same_line_and_line_above(self, tmp_path):
        src = """
            import os
            A = os.environ.get("X")  # dpxlint: disable=DPX002 legacy site
            # dpxlint: disable=DPX002 migration pending
            B = os.environ.get("Y")
            C = os.environ.get("Z")
        """
        findings = _lint_snippet(tmp_path, src)
        assert len(findings) == 1 and findings[0].rule == "DPX002"
        assert "Z" in findings[0].line_text

    def test_disable_reason_with_uppercase_words(self, tmp_path):
        src = """
            import os
            A = os.environ.get("X")  # dpxlint: disable=DPX002 IO path, PR 5
        """
        assert _lint_snippet(tmp_path, src) == []

    def test_disable_file(self, tmp_path):
        src = """
            '''module doc'''
            # dpxlint: disable-file=DPX002 standalone shim
            import os
            A = os.environ.get("X")
            B = os.environ.get("Y")
        """
        assert _lint_snippet(tmp_path, src) == []

    def test_disable_does_not_leak_to_other_rules(self, tmp_path):
        src = """
            import os
            import threading
            t = threading.Thread(target=print)  # dpxlint: disable=DPX002 wrong rule
        """
        assert "DPX005" in _rules(_lint_snippet(tmp_path, src))


class TestBaseline:
    def test_baseline_absorbs_then_new_findings_surface(self, tmp_path):
        src = """
            import os
            A = os.environ.get("X")
        """
        findings = _lint_snippet(tmp_path, src)
        assert len(findings) == 1
        bl = tmp_path / "baseline.json"
        lint.save_baseline(str(bl), findings)
        assert lint.apply_baseline(findings,
                                   lint.load_baseline(str(bl))) == []
        # a NEW finding (different line text) is not absorbed
        src2 = src + "B = os.environ.get(\"Y\")\n"
        findings2 = _lint_snippet(tmp_path, src2,
                                  rel="distributed_pytorch_tpu/mod2.py")
        # baseline paths differ -> nothing absorbed; rebuild on same path
        path = tmp_path / "distributed_pytorch_tpu" / "mod.py"
        path.write_text(path.read_text()
                        + "B = os.environ.get(\"Y\")\n")
        findings3 = lint.lint_paths([str(path)], root=str(tmp_path))
        fresh = lint.apply_baseline(findings3, lint.load_baseline(str(bl)))
        assert len(fresh) == 1 and "Y" in fresh[0].line_text
        assert len(findings2) == 1  # sanity: the other file also finds it

    def test_baseline_is_line_number_insensitive(self, tmp_path):
        src = """
            import os
            A = os.environ.get("X")
        """
        findings = _lint_snippet(tmp_path, src)
        bl = tmp_path / "b.json"
        lint.save_baseline(str(bl), findings)
        # shift the offending line down; fingerprint (rule,path,text) holds
        path = tmp_path / "distributed_pytorch_tpu" / "mod.py"
        path.write_text("import os\n\n\n\nA = os.environ.get(\"X\")\n")
        moved = lint.lint_paths([str(path)], root=str(tmp_path))
        assert lint.apply_baseline(moved, lint.load_baseline(str(bl))) == []

    def test_committed_baseline_entries_match_schema(self):
        path = os.path.join(lint.repo_root(), lint.DEFAULT_BASELINE)
        with open(path) as f:
            entries = json.load(f)
        for e in entries:
            assert {"rule", "path", "line_text"} <= set(e)


def test_repo_is_clean_under_committed_baseline():
    """THE acceptance gate: `python -m tools.dpxlint` exits 0 on this
    repo — zero findings outside the committed baseline."""
    from tools.dpxlint import main
    assert main([]) == 0


def test_cli_reports_deliberately_broken_fixture(tmp_path, capsys):
    bad = tmp_path / "distributed_pytorch_tpu" / "bad.py"
    bad.parent.mkdir(parents=True)
    bad.write_text("import os\nX = os.environ.get('A')\n")
    findings = lint.lint_paths([str(bad)], root=str(tmp_path))
    assert _rules(findings) == ["DPX002"]


def test_cli_exit_2_on_unparseable_file(tmp_path, capsys):
    """DPX000 contract regression: a file that fails to PARSE was not
    linted, so the CLI must exit 2 — not pretend the file is clean."""
    from tools.dpxlint import main
    broken = tmp_path / "broken.py"
    broken.write_text("def f(:\n")
    assert main([str(broken)]) == 2
    err = capsys.readouterr().err
    assert "DPX000" in err and "syntax error" in err


def test_cli_write_baseline_exit_2_on_unparseable_file(tmp_path, capsys):
    """The subtler half of the DPX000 contract: --write-baseline over an
    unparseable file must ALSO exit 2 — accepting a baseline that
    silently excludes an unlinted file would launder its findings."""
    from tools.dpxlint import main
    broken = tmp_path / "broken.py"
    broken.write_text("def f(:\n")
    bl = tmp_path / "bl.json"
    assert main(["--write-baseline", "--baseline", str(bl),
                 str(broken)]) == 2
    assert "DPX000" in capsys.readouterr().err
    # the baseline itself is still written (without the unparsed file)
    assert json.load(open(bl)) == []


def test_cli_format_json(tmp_path, capsys):
    from tools.dpxlint import main
    broken = tmp_path / "broken.py"
    broken.write_text("def f(:\n")
    assert main(["--format", "json", str(broken)]) == 2
    out = capsys.readouterr().out
    entries = json.loads(out)
    assert len(entries) == 1
    e = entries[0]
    assert e["rule"] == "DPX000" and e["line"] == 1
    assert {"rule", "path", "line", "message", "line_text"} <= set(e)


def test_cli_format_github_annotations(tmp_path, capsys):
    from tools.dpxlint import main
    broken = tmp_path / "broken.py"
    broken.write_text("def f(:\n")
    assert main(["--format", "github", str(broken)]) == 2
    out = capsys.readouterr().out
    assert out.startswith("::error file=")
    assert ",line=1,title=DPX000::" in out


def test_format_findings_escapes_github_message():
    f = lint.Finding(rule="DPX999", path="a.py", line=3,
                     message="bad%thing\nsecond line", line_text="x")
    out = lint.format_findings([f], "github")
    assert out == "::error file=a.py,line=3,title=DPX999::bad%25thing%0Asecond line"
    assert "\n" not in out  # one annotation per line, newlines escaped


def test_env_docs_current():
    """docs/env_vars.md is generated from the registry and committed;
    drift fails tier-1 (regenerate with `python -m tools.gen_env_docs`)."""
    from tools.gen_env_docs import main
    assert main(["--check"]) == 0


def test_env_registry_rejects_unknown_and_conflicts():
    from distributed_pytorch_tpu.runtime import env
    with pytest.raises(KeyError, match="not registered"):
        env.get("DPX_DOES_NOT_EXIST")
    with pytest.raises(ValueError, match="conflicting"):
        env.register("DPX_COMM_TIMEOUT_MS", "int", 1, "conflict")
    # idempotent identical re-registration is fine
    var = env.REGISTRY["DPX_SCHEDULE_WINDOW"]
    env.register(var.name, var.type, var.default, var.doc, var.external)


def test_env_typed_parse_and_malformed_fallback(monkeypatch):
    from distributed_pytorch_tpu.runtime import env
    monkeypatch.setenv("DPX_COMM_TIMEOUT_MS", "1234")
    assert env.get("DPX_COMM_TIMEOUT_MS") == 1234
    monkeypatch.setenv("DPX_COMM_TIMEOUT_MS", "garbage")
    assert env.get("DPX_COMM_TIMEOUT_MS") == 300_000  # declared default
    monkeypatch.setenv("DPX_ELASTIC", "1")
    assert env.get("DPX_ELASTIC") is True


def test_static_schedule_extraction_and_parity():
    """The static half of the schedule verifier: extraction matches the
    known host front-door composition, and both front doors expose the
    full collective surface with only known native ops."""
    host = extract_schedules()
    assert host["barrier"] == ["barrier"]
    assert host["all_gather"] == ["gather", "broadcast"]
    assert host["gather"] == ["gather"]
    assert "allreduce_q8" in host["all_reduce"]  # the quant wire path
    assert host["reduce"] == ["allreduce", "reduce"]  # f64-exact + f32 paths
    assert check_front_door_parity() == []
