"""Elastic restart-from-checkpoint supervision (runtime/elastic.py):
crash mid-training, relaunch, resume from the latest checkpoint, and land
on the bit-exact same final state as an uninterrupted run."""

import json
import os

import numpy as np
import pytest

from distributed_pytorch_tpu.runtime import elastic
from distributed_pytorch_tpu.runtime.watchdog import WorkerFailure

STEPS = 6
CRASH_AT = 3


def _train_worker(workdir: str, crash_on_first: bool):
    """Module-level (spawn-picklable) training entrypoint: resume from
    the latest checkpoint when one exists; on the first elastic attempt
    optionally die mid-run like a preempted/OOM-killed worker."""
    import jax  # the spawn child re-imports; switch platform before use
    jax.config.update("jax_platforms", "cpu")
    os.environ.setdefault("DPX_CPU_DEVICES", "1")

    from distributed_pytorch_tpu import models, optim
    from distributed_pytorch_tpu.ops.losses import cross_entropy
    from distributed_pytorch_tpu.parallel import make_train_step
    from distributed_pytorch_tpu.runtime.elastic import elastic_attempt
    from distributed_pytorch_tpu.utils.checkpoint import (latest_step,
                                                          restore_checkpoint,
                                                          save_checkpoint)

    model = models.DummyModel(in_dim=1, hidden_dim=8, n_classes=4)
    opt = optim.adamw(1e-2)
    step_fn = make_train_step(_loss(model), opt, donate=False)

    params = model.init(jax.random.PRNGKey(0))
    st = opt.init(params)
    start = 0
    if latest_step(workdir) is not None:
        ck = restore_checkpoint(workdir, like_params=params,
                                like_opt_state=st)
        params, st, start = ck.params, ck.opt_state, ck.step

    rng = np.random.default_rng(7)
    batches = [(rng.random((4, 1), dtype=np.float32),
                rng.integers(0, 4, size=(4,)).astype(np.int32))
               for _ in range(STEPS)]

    losses = []
    for s in range(start, STEPS):
        params, st, loss, _ = step_fn(params, st, batches[s])
        losses.append(float(np.asarray(loss).sum()))
        save_checkpoint(workdir, s + 1, params, st)
        if crash_on_first and elastic_attempt() == 0 and s + 1 == CRASH_AT:
            os._exit(3)          # hard death: no cleanup, like a SIGKILL

    np.savez(os.path.join(workdir, "final.npz"),
             **{f"p{i}": np.asarray(l) for i, l in
                enumerate(jax.tree_util.tree_leaves(params))})
    with open(os.path.join(workdir, "losses.json"), "a") as f:
        f.write(json.dumps(losses) + "\n")


def _loss(model):
    import jax.numpy as jnp  # noqa: F401
    from distributed_pytorch_tpu.ops.losses import cross_entropy

    def loss_fn(p, batch):
        x, y = batch
        return cross_entropy(model.apply(p, x), y), {}
    return loss_fn


def _final(workdir):
    z = np.load(os.path.join(workdir, "final.npz"))
    return [z[k] for k in sorted(z.files)]


def test_crash_resume_matches_uninterrupted(tmp_path):
    crashed = str(tmp_path / "crashed")
    straight = str(tmp_path / "straight")
    os.makedirs(crashed), os.makedirs(straight)

    res = elastic.elastic_run(_train_worker, (crashed, True),
                              max_restarts=2, backoff_s=0.01,
                              env={"DPX_ELASTIC_TEST_LEAK": "x"})
    assert res.restarts == 1
    assert res.exitcodes == (3, 0)
    # the supervisor's own environment must be untouched (the child gets
    # the bookkeeping + caller env; the parent is not supervised)
    assert not elastic.is_elastic()
    assert "DPX_ELASTIC_TEST_LEAK" not in os.environ

    res2 = elastic.elastic_run(_train_worker, (straight, False),
                               max_restarts=0, backoff_s=0.01)
    assert res2 == elastic.ElasticResult(0, (0,))

    for a, b in zip(_final(crashed), _final(straight)):
        np.testing.assert_array_equal(a, b)

    # only the resumed attempt reaches the end (attempt 0 hard-died
    # before its write), and it continued from CRASH_AT, repeating no
    # step: its losses are exactly the uninterrupted run's tail
    runs = [json.loads(l)
            for l in open(os.path.join(crashed, "losses.json"))]
    assert [len(r) for r in runs] == [STEPS - CRASH_AT]
    uninterrupted = json.loads(
        open(os.path.join(straight, "losses.json")).readline())
    assert runs[0] == pytest.approx(uninterrupted[CRASH_AT:], abs=0)


def _always_dies():
    os._exit(1)


def test_gives_up_after_max_restarts():
    with pytest.raises(WorkerFailure, match="failed 3 times") as ei:
        elastic.elastic_run(_always_dies, max_restarts=2, backoff_s=0.0)
    assert ei.value.exitcode == 1  # structured attribution for tooling


def _sleeps_long():
    import time
    time.sleep(120)


import multiprocessing as _mp  # noqa: E402


class _InterruptOnJoinProcess(_mp.get_context("spawn").Process):
    """First blocking join() raises KeyboardInterrupt (the operator's ^C
    landing in the supervisor); later joins behave normally. Module-level
    so the spawn pickling of the process object still works."""

    def join(self, timeout=None):
        if timeout is None and not getattr(self, "_interrupted", False):
            self._interrupted = True
            raise KeyboardInterrupt
        return super().join(timeout)


def test_supervisor_interrupt_does_not_leak_child(monkeypatch):
    """A KeyboardInterrupt (or any supervisor-side exception) during the
    join must terminate + reap the child instead of orphaning it with
    its ports/checkpoint dir (regression: elastic.py:87-91 had no
    try/finally around p.join())."""
    spawned = []

    class InterruptingCtx:
        def Process(self, *a, **k):
            p = _InterruptOnJoinProcess(*a, **k)
            spawned.append(p)
            return p

    monkeypatch.setattr(elastic.mp, "get_context",
                        lambda m: InterruptingCtx())
    with pytest.raises(KeyboardInterrupt):
        elastic.elastic_run(_sleeps_long, max_restarts=0, backoff_s=0.0)
    assert len(spawned) == 1
    p = spawned[0]
    try:
        # reaped on the way out: dead, with an exitcode collected
        assert not p.is_alive()
        assert p.exitcode is not None
    finally:
        if p.is_alive():
            p.kill()
            super(_InterruptOnJoinProcess, p).join()


def test_attempt_helpers_default_outside_elastic(monkeypatch):
    monkeypatch.delenv(elastic.ATTEMPT_ENV, raising=False)
    monkeypatch.delenv(elastic.ELASTIC_ENV, raising=False)
    assert elastic.elastic_attempt() == 0
    assert not elastic.is_elastic()
