"""The evaluation-ladder examples (ResNet-18, Transformer-LM) end-to-end
on the 8-device virtual mesh — BASELINE.md rungs 3 and 4. Small shapes;
asserts finite, recorded losses and the data-plumbing contracts."""

import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "examples"))

import distributed_pytorch_tpu as dist  # noqa: E402
import train_resnet  # noqa: E402
import train_transformer_lm  # noqa: E402


def test_transformer_lm_dp():
    h = []
    dist.launch(train_transformer_lm.main_worker,
                ["--steps", "4", "--batch-size", "1", "--seq-len", "16",
                 "--dim", "16", "--n-layers", "1", "--n-heads", "2",
                 "--data-size", "64"], True, h)
    assert len(h) == 4
    assert all(np.isfinite(x) for x in h)


def test_transformer_lm_fsdp_flash():
    h = []
    dist.launch(train_transformer_lm.main_worker,
                ["--steps", "4", "--batch-size", "1", "--seq-len", "16",
                 "--dim", "16", "--n-layers", "1", "--n-heads", "2",
                 "--data-size", "64", "--fsdp", "--flash"], True, h)
    assert len(h) == 4 and all(np.isfinite(x) for x in h)


def test_transformer_lm_byte_corpus(tmp_path):
    text = tmp_path / "corpus.txt"
    text.write_bytes(bytes(range(64)) * 40)
    corpus = train_transformer_lm.ByteCorpus(str(text), seq_len=16)
    x, y = corpus[0]
    assert x.shape == (16,) and y.shape == (16,)
    np.testing.assert_array_equal(y[:-1], x[1:])  # shifted-by-one targets
    h = []
    dist.launch(train_transformer_lm.main_worker,
                ["--steps", "3", "--batch-size", "1", "--seq-len", "16",
                 "--dim", "16", "--n-layers", "1", "--n-heads", "2",
                 "--text", str(text)], True, h)
    assert len(h) == 3 and all(np.isfinite(x) for x in h)


@pytest.mark.slow
def test_resnet_synthetic():
    h = []
    dist.launch(train_resnet.main_worker,
                ["--epochs", "2", "--batch-size", "2", "--data-size", "64",
                 "--limit-steps", "2"], True, h)
    assert len(h) == 4  # 2 epochs x 2 capped steps
    assert all(np.isfinite(x) for x in h)


def test_resnet_eval():
    h = []
    dist.launch(train_resnet.main_worker,
                ["--epochs", "1", "--batch-size", "2", "--data-size", "128",
                 "--limit-steps", "1", "--eval"], True, h)
    assert h and all(np.isfinite(x) for x in h)


def test_transformer_lm_eval_and_generate():
    h = []
    dist.launch(train_transformer_lm.main_worker,
                ["--steps", "3", "--batch-size", "1", "--seq-len", "16",
                 "--dim", "16", "--n-layers", "1", "--n-heads", "2",
                 "--data-size", "128", "--eval", "--generate", "4"], True, h)
    assert len(h) == 3 and all(np.isfinite(x) for x in h)


def test_resnet_missing_cifar_raises(tmp_path):
    with pytest.raises(FileNotFoundError):
        train_resnet.Cifar10(str(tmp_path))


def test_cifar10_reader(tmp_path):
    """The pickle-batch reader against a synthetic CIFAR-layout dir."""
    import pickle
    d = tmp_path / "cifar-10-batches-py"
    d.mkdir()
    rng = np.random.default_rng(0)
    for i in range(1, 6):
        data = rng.integers(0, 256, (20, 3072), dtype=np.uint8)
        with open(d / f"data_batch_{i}", "wb") as f:
            pickle.dump({b"data": data,
                         b"labels": list(rng.integers(0, 10, 20))}, f)
    ds = train_resnet.Cifar10(str(tmp_path))
    assert len(ds) == 100
    x, y = ds[0]
    assert x.shape == (32, 32, 3) and x.dtype == np.float32
    assert 0 <= int(y) < 10


def test_transformer_lm_checkpoint_resume_exact(tmp_path):
    """Interrupted-and-resumed training equals the uninterrupted run
    exactly: run A trains 9 steps straight; run B trains 5 steps saving at
    step 4, then a FRESH process state resumes from the checkpoint and
    finishes to 9. Loss histories for the continued steps must match
    bit-for-bit (same params, same opt state, same fast-forwarded data
    stream)."""
    ckpt = str(tmp_path / "ck")
    common = ["--batch-size", "2", "--seq-len", "16", "--dim", "16",
              "--n-layers", "1", "--n-heads", "2", "--data-size", "16",
              "--log-every", "1"]

    full = []
    dist.launch(train_transformer_lm.main_worker,
                ["--steps", "9"] + common, True, full)

    part = []
    dist.launch(train_transformer_lm.main_worker,
                ["--steps", "5", "--save", ckpt, "--save-every", "4"]
                + common, True, part)
    resumed = []
    dist.launch(train_transformer_lm.main_worker,
                ["--steps", "9", "--save", ckpt, "--resume",
                 "--save-every", "100"] + common, True, resumed)

    from distributed_pytorch_tpu.utils.checkpoint import latest_step
    # run B saved at 4 (interval) and force-saved at its last step
    assert latest_step(ckpt) == 8
    # resumed run continued at step 5..8 (4 steps)
    assert len(resumed) == 4
    np.testing.assert_array_equal(np.asarray(resumed),
                                  np.asarray(full[5:9]))


@pytest.mark.slow
@pytest.mark.parametrize("extra", [[], ["--sp-core", "striped"],
                                   ["--sp-core", "ulysses"],
                                   ["--window", "48"]])
def test_long_context_sp_modes(extra):
    """Sequence-parallel long-context training in every attention mode:
    contiguous ring-flash, striped (data-level token striping), ulysses
    (all-to-all), and sliding-window ring; loss finite over a few
    steps."""
    import train_long_context

    h = []
    train_long_context.main(
        ["--steps", "6", "--seq-len", "128", "--sp", "4",
         "--batch-size", "2", "--dim", "32", "--n-layers", "1",
         "--n-heads", "4", "--block-q", "16", "--block-k", "16"] + extra,
        quiet=True, history=h)
    assert len(h) == 5
    assert all(np.isfinite(x) for x in h)


@pytest.mark.slow
def test_transformer_lm_prefetch():
    """--prefetch N: batches arrive on device from the background thread;
    losses match the unprefetched run exactly (same data order)."""
    h0, h1 = [], []
    args = ["--steps", "6", "--batch-size", "1", "--seq-len", "16",
            "--dim", "16", "--n-layers", "1", "--n-heads", "2",
            "--data-size", "64", "--log-every", "1"]
    dist.launch(train_transformer_lm.main_worker, args, True, h0)
    dist.launch(train_transformer_lm.main_worker,
                args + ["--prefetch", "2"], True, h1)
    np.testing.assert_array_equal(np.asarray(h0), np.asarray(h1))


@pytest.mark.slow
@pytest.mark.parametrize("router", ["tokens", "experts"])
def test_moe_lm_example(router):
    """Expert-parallel MoE rung: dp x ep mesh, both routers; loss finite
    and decreasing over a few steps."""
    import train_moe_lm

    h = []
    train_moe_lm.main(
        ["--steps", "6", "--seq-len", "32", "--batch-size", "4",
         "--ep", "4", "--n-experts", "4", "--dim", "32", "--n-layers", "1",
         "--n-heads", "4", "--router", router],
        quiet=True, history=h)
    assert len(h) == 5
    assert all(np.isfinite(x) for x in h)
    assert h[-1] < h[0]
