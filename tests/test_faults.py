"""Chaos tests: deterministic fault injection (runtime/faults.py) driving
the typed comm-failure story end to end (ISSUE 2) — a rank hard-dying
mid-allreduce becomes a typed ``CommError`` on every survivor within 2x
the per-op deadline (never a hang), the supervisor reaps the world and
names the dead rank + op, and an elastic relaunch resumes bit-exact."""

import json
import multiprocessing as mp
import os
import sys
import threading
import time

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from distributed_pytorch_tpu.analysis import schedule
from distributed_pytorch_tpu.runtime import elastic, faults
from distributed_pytorch_tpu.runtime.multiprocess import launch_multiprocess
from distributed_pytorch_tpu.runtime.native import (CommError, CommPeerDied,
                                                    CommTimeout, HostComm)
from distributed_pytorch_tpu.runtime.watchdog import (HeartbeatMonitor,
                                                      StalledWorker,
                                                      WorkerFailure)

TIMEOUT_MS = 2000  # per-op deadline for the chaos runs


@pytest.fixture(autouse=True)
def _clean_faults(monkeypatch):
    """Every test starts with no faults installed and fresh counters."""
    monkeypatch.delenv(faults.FAULT_ENV, raising=False)
    faults.reset()
    yield
    faults.reset()


# ---------------------------------------------------------------------------
# spec grammar
# ---------------------------------------------------------------------------


class TestSpecGrammar:
    def test_parses_the_documented_specs(self):
        specs = faults.parse_fault_spec(
            "kill@step=3,rank=1;delay@op=allreduce,ms=500;drop_conn@step=2")
        assert [s.action for s in specs] == ["kill", "delay", "drop_conn"]
        assert specs[0].step == 3 and specs[0].rank == 1
        assert specs[1].op == "allreduce" and specs[1].ms == 500
        assert specs[2].step == 2

    def test_attempt_and_call_keys(self):
        (s,) = faults.parse_fault_spec("kill@op=allreduce,call=2,attempt=0")
        assert s.op == "allreduce" and s.call == 2 and s.attempt == 0

    @pytest.mark.parametrize("bad", [
        "explode@step=1",          # unknown action
        "kill@when=3",             # unknown key
        "kill@step",               # missing '='
        "delay@op=allreduce",      # delay without ms
    ])
    def test_malformed_specs_raise(self, bad):
        with pytest.raises(ValueError):
            faults.parse_fault_spec(bad)

    def test_unregistered_op_raises_typed_with_vocabulary(self):
        # the PR 17 bugfix: a typo'd op used to arm a clause that could
        # never fire — a chaos run silently testing nothing
        with pytest.raises(ValueError, match="unregistered fault op") as ei:
            faults.parse_fault_spec("kill@op=allredcue")
        msg = str(ei.value)
        assert "'allredcue'" in msg
        # the error must NAME the registered vocabulary, not just reject
        assert "allreduce" in msg and "handoff_send" in msg
        assert "faults.register_op" in msg

    def test_register_op_extends_the_vocabulary(self):
        with pytest.raises(ValueError, match="unregistered fault op"):
            faults.parse_fault_spec("delay@op=my_custom_op,ms=5")
        faults.register_op("my_custom_op")
        try:
            (s,) = faults.parse_fault_spec("delay@op=my_custom_op,ms=5")
            assert s.op == "my_custom_op"
            assert "my_custom_op" in faults.registered_ops()
        finally:
            faults._extra_ops.discard("my_custom_op")

    def test_count_only_valid_on_flaky(self):
        (s,) = faults.parse_fault_spec("flaky@op=handoff_send,count=3")
        assert s.action == "flaky" and s.count == 3
        with pytest.raises(ValueError, match="count"):
            faults.parse_fault_spec("kill@op=allreduce,count=3")


# ---------------------------------------------------------------------------
# hook semantics (in-process; `kill` is only exercised in subprocesses)
# ---------------------------------------------------------------------------


class _FakeComm:
    rank = 0

    def __init__(self):
        self.aborted = False

    def abort(self):
        self.aborted = True


class TestHooks:
    def test_delay_fires_on_matching_op(self):
        faults.install("delay@op=allreduce,ms=60")
        t0 = time.monotonic()
        faults.on_comm_op("allreduce", rank=0)
        assert time.monotonic() - t0 >= 0.05
        assert faults.fired() == ["delay@op=allreduce,call=1"]

    def test_op_and_rank_filters(self):
        faults.install("delay@op=allreduce,rank=1,ms=5000")
        t0 = time.monotonic()
        faults.on_comm_op("barrier", rank=1)   # wrong op
        faults.on_comm_op("allreduce", rank=0)  # wrong rank
        assert time.monotonic() - t0 < 1.0
        assert faults.fired() == []

    def test_call_filter_counts_per_op(self):
        faults.install("delay@op=reduce,call=2,ms=10")
        faults.on_comm_op("reduce", rank=0)
        assert faults.fired() == []
        faults.on_comm_op("reduce", rank=0)
        assert faults.fired() == ["delay@op=reduce,call=2"]

    def test_drop_conn_step_scoped_aborts_registered_comms(self):
        fake = _FakeComm()
        faults.register_comm(fake)
        faults.install("drop_conn@step=2")
        faults.on_step(1, rank=0)
        assert not fake.aborted
        faults.on_step(2, rank=0)
        assert fake.aborted
        # one-shot: a later step must not re-fire
        fake.aborted = False
        faults.on_step(2, rank=0)
        assert not fake.aborted

    def test_attempt_filter_respects_elastic_attempt(self, monkeypatch):
        fake = _FakeComm()
        faults.register_comm(fake)
        faults.install("drop_conn@step=1,attempt=0")
        monkeypatch.setenv(elastic.ATTEMPT_ENV, "1")
        faults.on_step(1, rank=0)
        assert not fake.aborted  # attempt 1 != 0: the relaunch runs clean
        monkeypatch.setenv(elastic.ATTEMPT_ENV, "0")
        faults.install("drop_conn@step=1,attempt=0")  # fresh (unfired) spec
        faults.on_step(1, rank=0)
        assert fake.aborted

    def test_rank_scoped_spec_never_fires_without_a_rank(self):
        """A hook that cannot say which rank it is must not fire a
        rank-scoped fault — 'just in case' would turn a one-rank kill
        into a whole-world kill."""
        faults.install("delay@op=allreduce,rank=1,ms=5000")
        t0 = time.monotonic()
        faults.on_comm_op("allreduce")  # rank unknown at this site
        faults.on_step(0)
        assert time.monotonic() - t0 < 1.0
        assert faults.fired() == []

    def test_step_scoped_kill_does_not_fire_on_other_ranks(self):
        # would os._exit the test process if the rank filter failed
        faults.install("kill@step=3,rank=1")
        faults.on_step(3, rank=0)
        faults.on_step(2, rank=1)
        assert faults.fired() == []


# ---------------------------------------------------------------------------
# native failure paths: typed errors instead of hangs
# ---------------------------------------------------------------------------


def test_rendezvous_timeout_exhaustion():
    """connect_with_retry gives up after timeout_ms: a missing master is a
    prompt typed error, not an infinite connect loop."""
    from distributed_pytorch_tpu.runtime.launcher import find_free_port

    port = find_free_port()  # nobody listens here
    t0 = time.monotonic()
    with pytest.raises(CommError, match="rendezvous failed"):
        HostComm("127.0.0.1", port, rank=1, world=2, timeout_ms=300)
    assert time.monotonic() - t0 < 10.0


def _report_and_reraise(q, rank, fn):
    """Run fn(); report (rank, error type, op, peer, elapsed) then re-raise
    so the supervisor sees the failure too. The queue is flushed before
    re-raising — the supervisor's teardown must not race the report."""
    t0 = time.monotonic()
    try:
        fn()
    except CommError as e:
        q.put((rank, type(e).__name__, e.op, e.peer,
               time.monotonic() - t0))
        q.close()
        q.join_thread()
        raise
    q.put((rank, None, None, None, time.monotonic() - t0))


def _peer_close_worker(rank, world, q):
    """Rank 1 is killed entering its first allreduce (DPX_FAULT, set by
    the parent); rank 0 must get CommPeerDied from the recv-0 path."""
    import numpy as np
    import distributed_pytorch_tpu as dist

    dist.init_process_group(rank, world)
    _report_and_reraise(
        q, rank, lambda: dist.all_reduce(np.ones(1024, np.float32)))


def test_send_recv_peer_close_raises_typed(monkeypatch):
    monkeypatch.setenv(faults.FAULT_ENV, "kill@op=allreduce,call=1,rank=1")
    monkeypatch.setenv("DPX_COMM_TIMEOUT_MS", str(TIMEOUT_MS))
    ctx = mp.get_context("spawn")
    q = ctx.Queue()
    with pytest.raises(WorkerFailure):
        launch_multiprocess(_peer_close_worker, 2, q)
    rank, kind, op, peer, elapsed = q.get(timeout=10)
    assert rank == 0
    assert kind == "CommPeerDied"
    assert op == "allreduce" and peer == 1
    assert elapsed < 2 * TIMEOUT_MS / 1000.0


def _delay_worker(rank, world, q):
    """Rank 1 stalls 30s entering its second allreduce; rank 0's deadline
    must fire as CommTimeout within the budget."""
    import numpy as np
    import distributed_pytorch_tpu as dist

    dist.init_process_group(rank, world)
    dist.all_reduce(np.ones(8, np.float32))  # call 1: clean
    _report_and_reraise(
        q, rank, lambda: dist.all_reduce(np.ones(8, np.float32)))


def test_wedged_peer_raises_comm_timeout(monkeypatch):
    monkeypatch.setenv(faults.FAULT_ENV,
                       "delay@op=allreduce,call=2,rank=1,ms=30000")
    monkeypatch.setenv("DPX_COMM_TIMEOUT_MS", "1000")
    ctx = mp.get_context("spawn")
    q = ctx.Queue()
    t0 = time.monotonic()
    with pytest.raises(WorkerFailure) as ei:
        launch_multiprocess(_delay_worker, 2, q)
    # the launch itself must not have waited out the 30s stall
    assert time.monotonic() - t0 < 25.0
    rank, kind, op, peer, elapsed = q.get(timeout=10)
    assert rank == 0 and kind == "CommTimeout"
    assert op == "allreduce" and peer == 1
    assert elapsed < 2 * 1.0  # within 2x the 1000ms deadline
    assert ei.value.op == "allreduce" and ei.value.kind == "CommTimeout"


def _drop_conn_worker(rank, world, q):
    """Rank 1 severs its own links entering allreduce call 2: rank 1 gets
    a local CommError, rank 0 observes peer-closed."""
    import numpy as np
    import distributed_pytorch_tpu as dist

    dist.init_process_group(rank, world)
    dist.all_reduce(np.ones(8, np.float32))
    _report_and_reraise(
        q, rank, lambda: dist.all_reduce(np.ones(8, np.float32)))


def test_drop_conn_propagates_to_both_sides(monkeypatch):
    monkeypatch.setenv(faults.FAULT_ENV,
                       "drop_conn@op=allreduce,call=2,rank=1")
    monkeypatch.setenv("DPX_COMM_TIMEOUT_MS", str(TIMEOUT_MS))
    ctx = mp.get_context("spawn")
    q = ctx.Queue()
    with pytest.raises(WorkerFailure):
        launch_multiprocess(_drop_conn_worker, 2, q)
    reports = {}
    for _ in range(2):
        rank, kind, op, peer, elapsed = q.get(timeout=10)
        reports[rank] = (kind, elapsed)
    assert reports[0][0] in ("CommPeerDied", "CommTimeout")
    assert reports[1][0] in ("CommError", "CommPeerDied")
    assert all(el < 2 * TIMEOUT_MS / 1000.0 for _, el in reports.values())


# ---------------------------------------------------------------------------
# THE chaos acceptance test: world 4, one rank killed mid-allreduce
# ---------------------------------------------------------------------------


def _chaos_worker(rank, world, q):
    """Two clean allreduces, then rank 2 is killed entering the third
    (mid-collective for everyone else: their deadline-guarded ring I/O is
    already in flight). Rank 2 on purpose — abort propagation cascades
    around the ring, so every survivor blames its own upstream neighbor
    and the supervisor must identify the dead rank as the blamed rank
    that never reported (min-of-blamed would wrongly pick rank 0 here)."""
    import numpy as np
    import distributed_pytorch_tpu as dist

    dist.init_process_group(rank, world)
    for _ in range(2):
        dist.all_reduce(np.ones(4096, np.float32))
    _report_and_reraise(
        q, rank, lambda: dist.all_reduce(np.ones(4096, np.float32)))


def test_chaos_kill_mid_allreduce_world4(monkeypatch):
    """Acceptance (ISSUE 2): DPX_FAULT kills rank 2 mid-allreduce in a
    world of 4. Every survivor raises a typed CommError subclass within
    2x DPX_COMM_TIMEOUT_MS (verified against a hard wall-clock bound —
    no hang), and WorkerFailure names the dead rank and the op."""
    monkeypatch.setenv(faults.FAULT_ENV, "kill@op=allreduce,call=3,rank=2")
    monkeypatch.setenv("DPX_COMM_TIMEOUT_MS", str(TIMEOUT_MS))
    ctx = mp.get_context("spawn")
    q = ctx.Queue()

    result = {}

    def run():
        try:
            launch_multiprocess(_chaos_worker, 4, q)
        except BaseException as e:  # noqa: BLE001
            result["exc"] = e

    t = threading.Thread(target=run, name="test-chaos-run", daemon=True)
    t.start()
    t.join(timeout=120)  # the hard no-hang bound for the whole world
    assert not t.is_alive(), "chaos run hung: deadline guard failed"
    assert isinstance(result.get("exc"), WorkerFailure)
    failure = result["exc"]
    # attribution: the DEAD rank and the op, not just "something exited"
    assert failure.rank == 2
    assert failure.op == "allreduce"
    assert "rank 2" in str(failure) and "allreduce" in str(failure)
    assert failure.exitcode == faults.KILL_EXIT_CODE

    reports = {}
    while len(reports) < 3:
        rank, kind, op, peer, elapsed = q.get(timeout=10)
        reports[rank] = (kind, op, peer, elapsed)
    assert set(reports) == {0, 1, 3}  # every survivor reported
    for rank, (kind, op, peer, elapsed) in reports.items():
        assert kind in ("CommPeerDied", "CommTimeout"), (rank, kind)
        assert op == "allreduce"
        assert elapsed < 2 * TIMEOUT_MS / 1000.0, (rank, elapsed)
    # rank 3 receives directly from rank 2 on the ring: it must blame it
    assert reports[3][2] == 2


# ---------------------------------------------------------------------------
# schedule verifier: an injected divergent collective is NAMED (rank/op/seq)
# ---------------------------------------------------------------------------


def test_diverge_spec_parses():
    (s,) = faults.parse_fault_spec("diverge@op=allreduce,call=3,rank=2")
    assert s.action == "diverge" and s.call == 3 and s.rank == 2


def test_diagnose_synthetic_events():
    """Unit semantics of the cross-rank join: agreement -> None; the
    first differing sequence point yields minority/majority attribution."""
    agree = [{"event": "comm_schedule", "rank": r, "digest": "d",
              "window": [[1, "allreduce|float32|8|sum"]]} for r in range(3)]
    assert schedule.diagnose(agree) is None
    assert schedule.diagnose(agree[:1]) is None  # one rank can't diverge

    events = []
    for r in range(4):
        sig3 = ("barrier|||" if r == 2 else "allreduce|float32|512|sum")
        events.append({
            "event": "comm_schedule", "rank": r, "digest": f"d{r}",
            "window": [[1, "allreduce|float32|512|sum"],
                       [2, "allreduce|float32|512|sum"], [3, sig3]]})
    rep = schedule.diagnose(events)
    assert rep is not None and rep.seq == 3
    assert rep.minority_ranks == [2] and rep.majority_ranks == [0, 1, 3]
    assert rep.minority_op.startswith("barrier")
    assert "rank 2" in str(rep) and "seq 3" in str(rep)

    # launches don't cross-contaminate: a stale flush from a PREVIOUS
    # launch (different tag, seq numbering restarted) must not be joined
    # against the newest launch's schedules — rank 0's old barrier here
    # would otherwise read as a divergence against run-2's allreduces
    stale = [{"event": "comm_schedule", "rank": 0, "digest": "old",
              "tag": "run-1", "window": [[1, "barrier|||"]]}]
    fresh = [{"event": "comm_schedule", "rank": r, "digest": "new",
              "tag": "run-2", "window": [[1, "allreduce|float32|8|sum"]]}
             for r in range(2)]
    assert schedule.diagnose(stale + fresh) is None  # newest tag only
    assert schedule.diagnose(stale + fresh, tag="run-1") is None  # 1 rank

    # malformed events in the shared stream are skipped, never raised on
    junk = [{"event": "comm_schedule", "rank": "not-a-rank",
             "tag": "run-2", "window": "nope"}]
    assert schedule.diagnose(stale + fresh + junk) is None


def _diverge_worker(rank, world, q):
    """Two clean allreduces; entering the third, rank 2's control flow
    'takes a different branch' (injected ``diverge``): it issues a
    barrier where ranks 0,1,3 issue allreduce #3 — the classic
    mismatched-collective-schedule bug, cut short by the deadline."""
    import numpy as np
    import distributed_pytorch_tpu as dist

    dist.init_process_group(rank, world)
    for _ in range(2):
        dist.all_reduce(np.ones(512, np.float32))
    _report_and_reraise(
        q, rank, lambda: dist.all_reduce(np.ones(512, np.float32)))


def test_schedule_verifier_names_divergent_rank_world4(tmp_path,
                                                       monkeypatch):
    """Acceptance (ISSUE 5): DPX_FAULT injects a divergent collective on
    rank 2 at allreduce call 3 in a world of 4. Everyone still fails
    typed within the deadline (PR 2's guarantee), but the flushed
    per-rank schedules now let the verifier name the diverging rank, op,
    and sequence number — and the supervisor logs that report
    automatically, alongside the worker_failure event, instead of
    leaving a bare CommTimeout."""
    log = str(tmp_path / "metrics.jsonl")
    monkeypatch.setenv("DPX_METRICS_LOG", log)
    monkeypatch.setenv(faults.FAULT_ENV,
                       "diverge@op=allreduce,call=3,rank=2")
    monkeypatch.setenv("DPX_COMM_TIMEOUT_MS", str(TIMEOUT_MS))
    ctx = mp.get_context("spawn")
    q = ctx.Queue()

    result = {}

    def run():
        try:
            launch_multiprocess(_diverge_worker, 4, q)
        except BaseException as e:  # noqa: BLE001
            result["exc"] = e

    t = threading.Thread(target=run, name="test-diverge-run", daemon=True)
    t.start()
    t.join(timeout=120)  # hard no-hang bound: divergence != deadlock
    assert not t.is_alive(), "diverge run hung: deadline guard failed"
    assert isinstance(result.get("exc"), WorkerFailure)

    # every rank raised typed; the diverging rank's own error names the
    # op it was actually stuck in (the barrier nobody else joined)
    reports = {}
    while len(reports) < 4:
        rank, kind, op, peer, elapsed = q.get(timeout=10)
        reports[rank] = (kind, op, elapsed)
    assert reports[2][1] == "barrier"
    for rank, (kind, op, elapsed) in reports.items():
        assert kind in ("CommTimeout", "CommPeerDied", "CommError"), (
            rank, kind)
        assert elapsed < 2 * TIMEOUT_MS / 1000.0, (rank, elapsed)

    # THE acceptance: the verifier names rank 2, the odd op, and seq 3
    rep = schedule.diagnose_log(log)
    assert rep is not None, "no divergence diagnosed from flushed schedules"
    assert rep.minority_ranks == [2]
    assert rep.minority_op.startswith("barrier")
    assert rep.majority_ranks == [0, 1, 3]
    assert rep.majority_op.startswith("allreduce|float32|512")
    assert rep.seq == 3
    s = str(rep)
    assert "rank 2" in s and "barrier" in s and "seq 3" in s

    # the supervisor ran the verifier with zero operator action: a
    # schedule_divergence event landed in the same line-JSON stream
    with open(log) as f:
        events = [json.loads(ln) for ln in f if ln.strip()]
    kinds = {e["event"] for e in events}
    assert "worker_failure" in kinds
    div = [e for e in events if e["event"] == "schedule_divergence"]
    assert div and div[0]["minority_ranks"] == [2] and div[0]["seq"] == 3


# ---------------------------------------------------------------------------
# heartbeat monitor vs a deliberately stalled (injected) rank
# ---------------------------------------------------------------------------


def _beating_worker(rank, hb_dir, steps):
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from distributed_pytorch_tpu.runtime import faults as child_faults
    from distributed_pytorch_tpu.runtime.watchdog import Heartbeat

    hb = Heartbeat(hb_dir, rank)
    for s in range(steps):
        child_faults.on_step(s, rank=rank)  # rank 1 stalls at step 2
        hb.beat(s)
        time.sleep(0.05)


def test_heartbeat_monitor_flags_stalled_injected_rank(tmp_path,
                                                       monkeypatch):
    """A rank stalled by an injected delay stops beating; the monitor's
    staleness check must name exactly that rank and assert_alive must
    raise StalledWorker (liveness alone cannot see a wedged-alive rank)."""
    monkeypatch.setenv(faults.FAULT_ENV, "delay@step=2,rank=1,ms=60000")
    d = str(tmp_path)
    ctx = mp.get_context("spawn")
    procs = [ctx.Process(target=_beating_worker, args=(r, d, 1200),
                         daemon=True) for r in range(2)]
    mon = HeartbeatMonitor(d, world_size=2)
    for p in procs:
        p.start()
    try:
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            if mon.stalled(timeout_s=1.0) == [1]:
                break
            time.sleep(0.1)
        assert mon.stalled(timeout_s=1.0) == [1]
        with pytest.raises(StalledWorker, match=r"\[1\]"):
            mon.assert_alive(1.0)
        assert procs[1].is_alive()  # wedged, not dead: the point
    finally:
        for p in procs:
            if p.is_alive():
                p.terminate()
            p.join()


# ---------------------------------------------------------------------------
# elastic relaunch after an injected mid-collective kill: bit-exact resume
# ---------------------------------------------------------------------------

_STEPS = 6


def _ckpt_train_worker(rank, world, workdir, steps):
    """Tiny deterministic 'training': params evolve by an all-reduced
    per-rank gradient each step; every rank checkpoints after every step.
    One allreduce per step => op call N belongs to step N-1."""
    import json

    import numpy as np
    import distributed_pytorch_tpu as dist
    from distributed_pytorch_tpu.runtime import faults as child_faults

    dist.init_process_group(rank, world)
    try:
        ck = os.path.join(workdir, f"rank{rank}.npz")
        if os.path.exists(ck):
            z = np.load(ck)
            params, start = z["params"], int(z["step"])
        else:
            params, start = np.full(64, 10.0, np.float32), 0
        for s in range(start, steps):
            child_faults.on_step(s, rank=rank)
            g = (params * 0.1 + rank + s).astype(np.float32)
            g = dist.all_reduce(g, op="avg")
            params = params - 0.1 * g
            loss = float(np.abs(params).mean())
            tmp = ck + ".tmp.npz"  # .npz suffix: savez must not append
            np.savez(tmp, params=params, step=s + 1)
            os.replace(tmp, ck)
            if rank == 0:
                with open(os.path.join(workdir, "losses.jsonl"), "a") as f:
                    f.write(json.dumps({"step": s, "loss": loss}) + "\n")
        if rank == 0:
            np.save(os.path.join(workdir, "final.npy"), params)
    finally:
        dist.cleanup()


def _elastic_target(workdir, steps):
    """The elastically supervised unit: a 2-rank native-DDP-style run."""
    launch_multiprocess(_ckpt_train_worker, 2, workdir, steps)


def _losses(workdir):
    import json
    with open(os.path.join(workdir, "losses.jsonl")) as f:
        return [(json.loads(l)["step"], json.loads(l)["loss"])
                for l in f if l.strip()]


@pytest.mark.slow
def test_chaos_elastic_relaunch_resumes_bit_exact(tmp_path, monkeypatch):
    """Acceptance (ISSUE 2), recovery half: after the injected
    mid-allreduce kill the supervisor reaps the world, elastic_run
    relaunches, and the relaunch resumes from checkpoint with a loss
    trajectory bit-exact to an uninterrupted run."""
    monkeypatch.setenv("DPX_COMM_TIMEOUT_MS", str(TIMEOUT_MS))
    crashed = str(tmp_path / "crashed")
    straight = str(tmp_path / "straight")
    os.makedirs(crashed), os.makedirs(straight)

    # one allreduce per step: call=4 kills rank 1 entering step 3's
    # collective, on elastic attempt 0 only — the relaunch runs clean
    res = elastic.elastic_run(
        _elastic_target, (crashed, _STEPS), max_restarts=2, backoff_s=0.05,
        env={faults.FAULT_ENV: "kill@op=allreduce,call=4,rank=1,attempt=0"})
    assert res.restarts == 1            # died once, recovered once
    assert res.exitcodes[0] != 0 and res.exitcodes[-1] == 0

    monkeypatch.delenv(faults.FAULT_ENV, raising=False)
    elastic.elastic_run(_elastic_target, (straight, _STEPS),
                        max_restarts=0, backoff_s=0.05)

    # bit-exact final params and a resumed (no step repeated, none
    # skipped) loss trajectory equal to the uninterrupted run's
    a = np.load(os.path.join(crashed, "final.npy"))
    b = np.load(os.path.join(straight, "final.npy"))
    np.testing.assert_array_equal(a, b)
    lc, ls = _losses(crashed), _losses(straight)
    assert [s for s, _ in lc] == [0, 1, 2, 3, 4, 5]
    assert lc == ls  # bit-exact losses, including the resumed tail


# ---------------------------------------------------------------------------
# failure events land in the line-JSON metrics log
# ---------------------------------------------------------------------------


def test_worker_failure_event_in_metrics_log(tmp_path, monkeypatch):
    import json

    log = tmp_path / "metrics.jsonl"
    monkeypatch.setenv("DPX_METRICS_LOG", str(log))
    monkeypatch.setenv(faults.FAULT_ENV, "kill@op=allreduce,call=1,rank=1")
    monkeypatch.setenv("DPX_COMM_TIMEOUT_MS", str(TIMEOUT_MS))
    ctx = mp.get_context("spawn")
    q = ctx.Queue()
    with pytest.raises(WorkerFailure):
        launch_multiprocess(_peer_close_worker, 2, q)
    rows = [json.loads(l) for l in log.read_text().splitlines()]
    ev = [r for r in rows if r["event"] == "worker_failure"]
    assert ev and ev[0]["rank"] == 1 and ev[0]["op"] == "allreduce"
    assert ev[0]["kind"] == "CommPeerDied"
