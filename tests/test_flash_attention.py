"""Flash-attention pallas kernel vs the dense reference implementation.

Values and gradients must match ``nn.attention.dense_attention`` (the
straightforward softmax(qk)v einsum) — causal and non-causal, block-aligned
and ragged sequence lengths, float32 and bfloat16. Runs in interpret mode
on the CPU test mesh; the same kernels compile on TPU.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_pytorch_tpu.nn.attention import dense_attention
from distributed_pytorch_tpu.ops import flash_attention, make_flash_attn_fn


def _qkv(key, b=2, h=2, s_q=64, s_k=64, d=16, dtype=jnp.float32):
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (b, h, s_q, d), dtype)
    k = jax.random.normal(kk, (b, h, s_k, d), dtype)
    v = jax.random.normal(kv, (b, h, s_k, d), dtype)
    return q, k, v


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("s_q,s_k,bq,bk", [
    (64, 64, 16, 16),     # block-aligned
    (50, 50, 16, 16),     # ragged: pad+mask path
    (32, 64, 16, 16),     # cross lengths (causal frontier offset)
])
def test_forward_matches_dense(causal, s_q, s_k, bq, bk):
    q, k, v = _qkv(jax.random.PRNGKey(0), s_q=s_q, s_k=s_k)
    want = dense_attention(q, k, v, causal=causal)
    got = flash_attention(q, k, v, causal=causal, block_q=bq, block_k=bk)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("s_q,s_k", [(64, 64), (50, 50)])
def test_grads_match_dense(causal, s_q, s_k):
    q, k, v = _qkv(jax.random.PRNGKey(1), s_q=s_q, s_k=s_k)

    def loss_dense(q, k, v):
        return jnp.sum(dense_attention(q, k, v, causal=causal) ** 2)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=causal,
                                       block_q=16, block_k=16) ** 2)

    want = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    got = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    for g, w, name in zip(got, want, "qkv"):
        np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                   atol=5e-4, rtol=5e-4,
                                   err_msg=f"d{name} mismatch")


@pytest.mark.parametrize("s_q,s_k,bq,bk", [
    (64, 32, 16, 16),    # whole q-tiles above the diagonal (body skipped)
    (40, 24, 16, 16),    # ragged + partially-masked tiles
    (64, 16, 64, 16),    # fully-masked rows inside an executed tile
])
def test_causal_sq_gt_sk_nan_rows_match_dense(s_q, s_k, bq, bk):
    """Causal with s_q > s_k: query rows above the shifted diagonal attend
    to nothing. Dense softmax over an all--inf row is NaN; the kernel must
    emit NaN for exactly those rows rather than a mean of masked-out v rows
    (regression: the _finish guard used to handle only the never-executed
    l==0 case)."""
    q, k, v = _qkv(jax.random.PRNGKey(6), s_q=s_q, s_k=s_k)
    want = np.asarray(dense_attention(q, k, v, causal=True))
    got = np.asarray(flash_attention(q, k, v, causal=True,
                                     block_q=bq, block_k=bk))
    nan_rows = np.isnan(want).all(axis=-1)
    assert nan_rows.any(), "case must exercise fully-masked rows"
    np.testing.assert_array_equal(np.isnan(got), np.isnan(want))
    np.testing.assert_allclose(got[~nan_rows], want[~nan_rows],
                               atol=2e-5, rtol=2e-5)


def test_bfloat16_close():
    q, k, v = _qkv(jax.random.PRNGKey(2), dtype=jnp.bfloat16)
    want = dense_attention(q, k, v, causal=True).astype(jnp.float32)
    got = flash_attention(q, k, v, causal=True, block_q=16,
                          block_k=16).astype(jnp.float32)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-2, rtol=2e-2)


def test_jit_and_scale_arg():
    q, k, v = _qkv(jax.random.PRNGKey(3))
    f = jax.jit(lambda q, k, v: flash_attention(q, k, v, scale=0.5,
                                                block_q=32, block_k=32))
    want = dense_attention(q, k, v, scale=0.5)
    np.testing.assert_allclose(np.asarray(f(q, k, v)), np.asarray(want),
                               atol=2e-5, rtol=2e-5)


def test_mha_with_flash_attn_fn():
    """A model built with make_flash_attn_fn matches the dense-core model."""
    from distributed_pytorch_tpu.nn.attention import MultiHeadAttention

    mha_dense = MultiHeadAttention(32, 4, causal=True)
    mha_flash = MultiHeadAttention(32, 4, causal=True,
                                   attn_fn=make_flash_attn_fn(16, 16, min_seq_flash=None))
    params = mha_dense.init(jax.random.PRNGKey(4))
    x = jax.random.normal(jax.random.PRNGKey(5), (2, 48, 32))
    want = mha_dense.apply(params, x)
    got = mha_flash.apply(params, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-5, rtol=2e-5)


def test_flash_min_seq_crossover_dispatch(monkeypatch):
    """Below min_seq_flash keys the attn_fn must run the dense einsum
    (the measured v5e crossover: flash loses to dense at seq 512,
    BASELINE.md round-3 table); at/above it, the kernel. Verified by
    counting kernel entries, and the two paths must agree numerically."""
    import importlib
    fa = importlib.import_module(
        "distributed_pytorch_tpu.ops.flash_attention")

    calls = {"kernel": 0}
    real = fa.flash_attention

    def counting(*a, **kw):
        calls["kernel"] += 1
        return real(*a, **kw)

    monkeypatch.setattr(fa, "flash_attention", counting)
    attn_fn = fa.make_flash_attn_fn(16, 16, min_seq_flash=64)

    q, k, v = _qkv(jax.random.PRNGKey(11), s_q=32, s_k=32)
    short = attn_fn(q, k, v, causal=True)
    assert calls["kernel"] == 0  # dense path took it
    np.testing.assert_allclose(
        np.asarray(short), np.asarray(dense_attention(q, k, v, causal=True)),
        atol=2e-5, rtol=2e-5)

    q, k, v = _qkv(jax.random.PRNGKey(12), s_q=64, s_k=64)
    long = attn_fn(q, k, v, causal=True)
    assert calls["kernel"] == 1  # kernel took it
    np.testing.assert_allclose(
        np.asarray(long), np.asarray(dense_attention(q, k, v, causal=True)),
        atol=2e-5, rtol=2e-5)

    # None disables the fallback entirely
    always = fa.make_flash_attn_fn(16, 16, min_seq_flash=None)
    q, k, v = _qkv(jax.random.PRNGKey(13), s_q=32, s_k=32)
    always(q, k, v, causal=True)
    assert calls["kernel"] == 2


@pytest.mark.parametrize("s_q,s_k,window,bq,bk", [
    (64, 64, 16, 16, 16),   # window spans exactly one tile
    (50, 50, 7, 16, 16),    # ragged length, window not tile-aligned
    (64, 64, 1, 16, 16),    # degenerate: attend to self only
    (48, 48, 100, 16, 16),  # window larger than sequence == plain causal
    (32, 64, 8, 16, 16),    # cross lengths: off > 0 shifts the band
    (24, 48, 5, 8, 8),      # cross lengths, ragged, small blocks
])
@pytest.mark.slow
def test_sliding_window_matches_dense(s_q, s_k, window, bq, bk):
    """Causal sliding-window attention: values AND grads match the dense
    masked reference (the lower-edge tile skip must agree with the mask
    in both backward kernels too, including the cross-length offset that
    shifts the whole band when s_q != s_k)."""
    q, k, v = _qkv(jax.random.PRNGKey(7), s_q=s_q, s_k=s_k)
    want = dense_attention(q, k, v, causal=True, window=window)
    got = flash_attention(q, k, v, causal=True, window=window,
                          block_q=bq, block_k=bk)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-5, rtol=2e-5)

    def lf(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=True,
                                       window=window, block_q=bq,
                                       block_k=bk) ** 2)

    def ld(q, k, v):
        return jnp.sum(dense_attention(q, k, v, causal=True,
                                       window=window) ** 2)

    g = jax.grad(lf, argnums=(0, 1, 2))(q, k, v)
    w = jax.grad(ld, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(g, w, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-4, rtol=5e-4,
                                   err_msg=f"d{name}")


def test_window_requires_causal():
    q, k, v = _qkv(jax.random.PRNGKey(8))
    with pytest.raises(ValueError, match="causal"):
        flash_attention(q, k, v, causal=False, window=8)
    with pytest.raises(ValueError, match="causal"):
        dense_attention(q, k, v, causal=False, window=8)
