"""The spec-driven front-door suite (ISSUE 13).

ONE parametrized matrix over ``(mesh, spec, wire, weight_update)``
replaces the per-front-door duplicate matrices that accumulated since
PR 7 (``test_sharded_optim.py``'s SPMD/host twins and
``test_adaptive_collectives.py``'s SPMD q4/adaptive pair): every point
is built through the same ``parallel.front_door.make_step`` spec
resolution and held to the same oracle — the exact replicated-mean
trajectory — plus the two front-door contracts the refactor exists
for:

* **compile counters**: one program per (mesh, spec, width) point,
  asserted via trace-time counters, never trusted;
* **donation + reshard-free handoff**: params/opt state donated with
  out == in shardings (XLA ``memory_analysis`` alias/peak bytes as
  evidence), and the train -> eval -> serve-admit chain moving zero
  bytes between pjit programs (``verify_handoff`` + pinned eval/admit
  shardings), at world 1 and on a virtual mesh of 4 (the CI
  ``front-door-contract`` step).

The builder-cache regression (a kwargs combo missing the cache and
silently dropping donation) is pinned by TestBuilderCache.
"""

import multiprocessing as mp
import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
from jax.sharding import PartitionSpec as P  # noqa: E402

import distributed_pytorch_tpu as dist  # noqa: E402
from distributed_pytorch_tpu import models, optim  # noqa: E402
from distributed_pytorch_tpu.ops.losses import cross_entropy  # noqa: E402
from distributed_pytorch_tpu.parallel import (  # noqa: E402
    FROM_INPUTS, HandoffMismatch, StepSpecs, front_door, handoff_shardings,
    make_train_step, make_step, shard_layouts, verify_handoff)
from distributed_pytorch_tpu.runtime import context  # noqa: E402
from distributed_pytorch_tpu.runtime.multiprocess import (  # noqa: E402
    launch_multiprocess)


@pytest.fixture(autouse=True)
def _fresh_cache():
    front_door.cache_clear()
    yield
    front_door.cache_clear()


def _setup(hidden=32, in_dim=1, seed=0):
    model = models.DummyModel(in_dim=in_dim, hidden_dim=hidden,
                              n_classes=4)
    params = model.init(jax.random.PRNGKey(seed))
    opt = optim.adamw(1e-3)

    def loss_fn(p, batch):
        x, y = batch
        return cross_entropy(model.apply(p, x), y), {}

    return model, params, opt, loss_fn


def _batch(in_dim=1, n=16):
    rng = np.random.default_rng(3)
    x = dist.shard_batch(rng.random((n, in_dim)).astype(np.float32))
    y = dist.shard_batch((np.arange(n) % 4).astype(np.int32))
    return (x, y)


def _run(step, params, opt_state, batch, steps=5):
    losses = []
    p, s = params, opt_state
    for _ in range(steps):
        out = step(p, s, batch)
        p, s = out.params, out.opt_state
        losses.append(float(np.asarray(out.loss).mean()))
    return p, losses


# ---------------------------------------------------------------------------
# builder cache + donation (the satellite-4 regression class)
# ---------------------------------------------------------------------------


class TestBuilderCache:
    def test_same_config_returns_cached_step_no_retrace(self, group8):
        model, params, opt, loss_fn = _setup()
        batch = _batch()
        a = make_step(loss_fn, opt)
        b = make_step(loss_fn, opt)
        assert a is b, "identical config must hit the builder cache"
        st = opt.init(params)
        out = a(params, st, batch)
        out = b(out.params, out.opt_state, batch)
        # the cached step is ONE program, traced once — a silent
        # re-trace (the old per-call-rebuild behavior) would bump this
        assert a.compiles == 1, a.trace_counts

    def test_donate_is_part_of_the_cache_key(self, group8):
        """The regression this suite pins: re-entering the builder with
        a different kwargs combo must NOT hand back a program built
        under other flags — donation in particular. Keyed on the full
        config tuple; proven by XLA's own aliasing accounting."""
        model, params, opt, loss_fn = _setup()
        batch = _batch()
        don = make_step(loss_fn, opt, donate=True)
        cop = make_step(loss_fn, opt, donate=False)
        assert don is not cop
        assert don.donated and not cop.donated
        st = opt.init(params)
        ma_d = don.memory_analysis(params, st, batch)
        ma_c = cop.memory_analysis(params, st, batch)
        assert ma_d["alias"] > 0, "donated build must alias in->out"
        assert ma_c["alias"] == 0, "copy build must not alias"
        assert ma_d["peak_bytes"] < ma_c["peak_bytes"]
        # and a third spelling of the same donate=True config still
        # hits the first build
        assert make_step(loss_fn, opt, donate=True) is don

    def test_wire_mp_and_specs_are_keyed(self, group8):
        model, params, opt, loss_fn = _setup()
        a = make_step(loss_fn, opt, donate=False)
        assert make_step(loss_fn, opt, wire="quant",
                         donate=False) is not a
        assert make_step(loss_fn, opt, mixed_precision="bf16",
                         donate=False) is not a
        assert make_step(loss_fn, opt, specs=FROM_INPUTS,
                         donate=False) is not a

    def test_donated_input_is_consumed(self, group8):
        """Donation is real, not a flag: the donated params buffer is
        deleted after the step (reuse would read clobbered memory)."""
        model, params, opt, loss_fn = _setup()
        batch = _batch()
        step = make_step(loss_fn, opt, donate=True)
        p = jax.device_put(params, context.replicated_sharding())
        st = opt.init(p)
        leaf_before = jax.tree_util.tree_leaves(p)[0]
        out = step(p, st, batch)
        assert leaf_before.is_deleted()
        # out == in shardings: the returned params carry exactly the
        # sharding the step pins on its inputs
        verify_handoff(out.params, handoff_shardings(step))

    def test_dpx_donate_env_default(self, group8, monkeypatch):
        model, params, opt, loss_fn = _setup()
        monkeypatch.setenv("DPX_DONATE", "0")
        off = make_step(loss_fn, opt)
        assert not off.donated
        monkeypatch.delenv("DPX_DONATE")
        on = make_step(loss_fn, opt)
        assert on.donated and on is not off


# ---------------------------------------------------------------------------
# the spec-driven matrix (mesh door) — one suite, every spec point
# ---------------------------------------------------------------------------

#: (name, wire, weight_update, rtol) — the dp points of the matrix.
DP_POINTS = [
    ("mean-replicated", "mean", "replicated", 1e-6),
    ("quant-replicated", "quant", "replicated", 5e-2),
    ("q4-replicated", "q4", "replicated", 2e-1),
    ("adaptive-replicated", "adaptive", "replicated", 5e-2),
    ("mean-sharded", "mean", "sharded", 1e-4),
    ("quant-sharded", "quant", "sharded", 5e-2),
]


class TestSpecMatrix:
    """Every (spec, wire, weight_update) point tracks the exact
    replicated oracle and compiles exactly one program per width."""

    def _oracle(self, loss_fn, opt, params, batch):
        step = make_step(loss_fn, opt, donate=False)
        _, losses = _run(step, params, opt.init(params), batch)
        return losses

    @pytest.mark.parametrize("name,wire,wu,rtol",
                             DP_POINTS, ids=[p[0] for p in DP_POINTS])
    def test_dp_point_tracks_oracle(self, group8, name, wire, wu, rtol):
        model, params, opt, loss_fn = _setup()
        batch = _batch()
        oracle = self._oracle(loss_fn, opt, params, batch)
        step = make_step(loss_fn, opt, wire=wire, weight_update=wu,
                         donate=False)
        st = (step.init_opt_state(params) if wu == "sharded"
              else opt.init(params))
        _, losses = _run(step, params, st, batch)
        np.testing.assert_allclose(losses, oracle, rtol=rtol, atol=rtol)
        # ONE program per (mesh, spec, width) point: adaptive owns one
        # per width it actually ran, every other point exactly one
        assert all(n == 1 for n in step.trace_counts.values()), \
            step.trace_counts
        if wire == "adaptive":
            assert step.width_chooser is not None
            assert set(step.width_chooser.widths) <= {4, 8}
            assert len(step.trace_counts) <= 2
        else:
            assert step.compiles == 1

    def test_adaptive_converges_to_q4_and_keeps_programs_bounded(
            self, group8):
        """Gaussian gradients drop to q4 after the hysteresis — and the
        width flip compiles exactly one more program, not one per
        step (the bounded-variants discipline)."""
        model, params, opt, loss_fn = _setup()
        batch = _batch()
        step = make_step(loss_fn, opt, wire="adaptive", donate=False)
        _, _ = _run(step, params, opt.init(params), batch, steps=6)
        widths = step.width_chooser.widths
        assert widths[:2] == [8, 8]       # starts safe, hysteresis 2
        assert all(n == 1 for n in step.trace_counts.values())

    @pytest.mark.parametrize("rung", ["zero3", "zero1", "zero2"])
    def test_constraint_ladder_tracks_oracle(self, group8, rung):
        """The fsdp ladder as front-door spec points, resolved through
        the shard_layouts/opt_state_specs contract. Loss is the global
        scalar (GSPMD view) — equal to the stacked oracle's mean."""
        model, params, opt, loss_fn = _setup(hidden=64, in_dim=8)
        batch = _batch(in_dim=8)
        oracle = self._oracle(loss_fn, opt, params, batch)
        opt_state = opt.init(params)
        p_specs, o_specs, axes = shard_layouts(
            params, opt_state, n_shards=8, min_size=64)
        assert axes == {"dp": 8}
        from distributed_pytorch_tpu.parallel.tensor import \
            replicated_specs
        if rung == "zero3":
            specs = StepSpecs(params=p_specs)
        elif rung == "zero2":
            specs = StepSpecs(params=replicated_specs(params),
                              opt=p_specs, grads=p_specs)
        else:
            specs = StepSpecs(params=replicated_specs(params),
                              opt=p_specs,
                              grads=replicated_specs(params))
        step = make_step(loss_fn, opt, mesh=context.get_mesh(),
                         specs=specs, donate=False)
        _, losses = _run(step, params, opt_state, batch)
        np.testing.assert_allclose(losses, oracle, rtol=2e-5, atol=1e-6)
        assert step.compiles == 1, step.trace_counts
        # the ladder's memory claim is XLA-visible: the sharded-state
        # rungs pin the opt state to 1/8 leaves (spec P('dp') on the
        # big leaves), and out shardings == in shardings
        assert step.out_shardings["opt"] == step.in_shardings["opt"]
        assert step.out_shardings["params"] == step.in_shardings["params"]

    def test_sharded_state_specs_flow_to_ckpt_contract(self, group8):
        """weight_update='sharded' through the front door keeps the
        checkpoint-facing exports (state_specs/init_opt_state)."""
        model, params, opt, loss_fn = _setup()
        step = make_step(loss_fn, opt, weight_update="sharded",
                         donate=False)
        st = step.init_opt_state(params)
        specs = step.state_specs(st)
        assert specs.master == P("dp")
        assert specs.inner.mu == P("dp")
        assert specs.inner.step == P()


# ---------------------------------------------------------------------------
# the host door points of the same matrix (per-rank processes, world 2)
# ---------------------------------------------------------------------------


def _host_matrix_worker(rank, world, q, wire, wu, steps):
    """One (wire, weight_update) point on the host door: the reference
    DDP workload stepped through the SAME make_step spec resolution;
    reports the loss trajectory, a bitwise param digest (ranks must
    never drift), and per-op CommStats bytes (the wire accounting)."""
    import hashlib

    import jax as _jax
    import numpy as _np

    import distributed_pytorch_tpu as _dist
    from distributed_pytorch_tpu import models as _models
    from distributed_pytorch_tpu import optim as _optim
    from distributed_pytorch_tpu.ops.losses import cross_entropy as _ce
    from distributed_pytorch_tpu.parallel import make_step as _mk
    from distributed_pytorch_tpu.runtime import context as _ctx

    _dist.init_process_group(rank, world)
    try:
        model = _models.DummyModel(in_dim=1, hidden_dim=32, n_classes=4)
        params = model.init(_jax.random.PRNGKey(0))
        opt = _optim.adamw(1e-2)

        def loss_fn(p, batch):
            x, y = batch
            return _ce(model.apply(p, x), y), {}

        rng = _np.random.default_rng(0)
        x = rng.random((16, 1), dtype=_np.float32)
        y = rng.integers(0, 4, (16,)).astype(_np.int32)
        lo = rank * (16 // world)
        hi = lo + 16 // world
        step = _mk(loss_fn, opt, wire=wire, weight_update=wu)
        st = (step.init_opt_state(params)
              if hasattr(step, "init_opt_state")
              and wu == "sharded" else opt.init(params))
        losses = []
        for _ in range(steps):
            out = step(params, st, (x[lo:hi], y[lo:hi]))
            params, st = out.params, out.opt_state
            losses.append(float(_np.asarray(out.loss)[0]))
        digest = hashlib.sha256(b"".join(
            _np.ascontiguousarray(_np.asarray(l, _np.float32)).tobytes()
            for l in _jax.tree_util.tree_leaves(params))).hexdigest()
        comm = _ctx.get_host_comm()
        stats = {k: int(v["bytes"])
                 for k, v in comm.stats.summary().items()}
        widths = (step.width_chooser.widths
                  if getattr(step, "width_chooser", None) else None)
        q.put((rank, digest, losses, stats, widths))
    finally:
        _dist.cleanup()


_host_cache = {}


def _run_host_point(wire, wu, world=2, steps=4):
    key = (wire, wu, world, steps)
    if key in _host_cache:       # the replicated baseline is shared
        return _host_cache[key]
    ctx = mp.get_context("spawn")
    q = ctx.Queue()
    launch_multiprocess(_host_matrix_worker, world, q, wire, wu, steps)
    res = {}
    while len(res) < world:
        rank, digest, losses, stats, widths = q.get(timeout=120)
        res[rank] = (digest, losses, stats, widths)
    # ranks never drift apart, at any spec point
    assert len({v[0] for v in res.values()}) == 1, (wire, wu)
    _host_cache[key] = res[0]
    return res[0]


class TestHostMatrix:
    def test_sharded_mean_tracks_replicated(self):
        rep = _run_host_point("mean", "replicated")
        sh = _run_host_point("mean", "sharded")
        np.testing.assert_allclose(sh[1], rep[1], rtol=1e-5, atol=1e-6)

    def test_adaptive_replicated_tracks_and_agrees_on_widths(self):
        rep = _run_host_point("mean", "replicated")
        ad = _run_host_point("adaptive", "replicated")
        np.testing.assert_allclose(ad[1], rep[1], rtol=5e-2, atol=5e-2)
        # hysteresis: starts at q8; the chooser state is rank-agreed
        # (digest equality above pins the params; widths recorded)
        assert ad[3] is not None and ad[3][:2] == [8, 8]
        assert set(ad[3]) <= {4, 8}

    @pytest.mark.slow
    def test_sharded_quant_tracks_and_books_leg_bytes(self):
        """Quant wire + sharded update on the host door: trajectory
        tracks, and CommStats recorded the reduce_scatter/allgather
        legs at exactly the wire.py accounting (bytes-on-wire asserted,
        not narrated). Slow tier: the leg byte accounting is also
        asserted process-free by test_sharded_optim.TestWireLegSpecs
        and end to end by the CI bench smoke."""
        from distributed_pytorch_tpu.comm import wire

        rep = _run_host_point("mean", "replicated")
        shq = _run_host_point("quant", "sharded")
        np.testing.assert_allclose(shq[1], rep[1], rtol=5e-2, atol=5e-2)
        stats = shq[2]
        assert "reduce_scatter" in stats and "allgather" in stats
        # DummyModel flat bucket at world 2: 4 leaves x 1 block each
        n_padded = 4 * wire.QUANT_BLOCK
        leg = wire.quant_leg_wire_bytes(n_padded, 2) // 2
        assert stats["reduce_scatter"] == 4 * leg  # 4 steps
        assert stats["allgather"] == 4 * leg


# ---------------------------------------------------------------------------
# the train -> eval -> serve-admit handoff chain (world 1 + mesh 4)
# ---------------------------------------------------------------------------


class TestHandoffChain:
    def _lm_setup(self):
        model = models.TransformerLM(vocab=64, dim=32, n_layers=2,
                                     n_heads=2, pos="rope", max_seq=64)
        params = model.init(jax.random.PRNGKey(0))
        opt = optim.adamw(1e-3)

        def loss_fn(p, batch):
            tokens = batch
            logits = model.apply(p, tokens[:, :-1])
            return cross_entropy(
                logits.reshape(-1, 64), tokens[:, 1:].reshape(-1)), {}

        return model, params, opt, loss_fn

    def _chain(self, world):
        """Train -> eval -> serve-admit with zero resharding, asserted
        at every joint by verify_handoff + compile counters."""
        from distributed_pytorch_tpu.serve import (EngineConfig,
                                                   InferenceEngine,
                                                   SamplingParams)

        if world > 1:
            dist.init_process_group(rank=0, world_size=world)
        try:
            model, params, opt, loss_fn = self._lm_setup()
            rng = np.random.default_rng(0)
            tokens = dist.shard_batch(
                rng.integers(0, 64, (8, 17)).astype(np.int32))
            step = make_train_step(loss_fn, opt)   # donation default ON
            st = opt.init(params)
            out = step(params, st, tokens)
            out = step(out.params, out.opt_state, tokens)
            assert step.compiles == 1, step.trace_counts
            p_sh = handoff_shardings(step)
            # train -> eval: pinned in_shardings, zero copies
            verify_handoff(out.params, p_sh)
            ev = front_door.make_eval_step(
                lambda p, b: model.apply(p, b).argmax(-1), like=step)
            pred = ev(out.params, tokens)
            pred = ev(out.params, tokens)
            assert np.asarray(pred).shape == (8, 17)
            assert ev.trace_counts["n"] == 1
            # eval -> serve admit: the engine pins the SAME shardings
            # and must accept the step's params verbatim (no copy:
            # verify_handoff returns the identical tree)
            eng = InferenceEngine(
                model, out.params,
                EngineConfig(n_slots=2, max_len=64, param_shardings=p_sh))
            assert jax.tree_util.tree_leaves(eng.params)[0] is \
                jax.tree_util.tree_leaves(out.params)[0]
            with eng:
                toks = eng.submit(
                    rng.integers(0, 64, (5,)).astype(np.int32),
                    SamplingParams(max_new_tokens=4),
                    rng=jax.random.PRNGKey(7)).result(timeout=120)
            assert len(toks) == 4
            assert eng.pool.compiles.decode == 1
            # a tree that does NOT carry the pinned shardings is
            # rejected typed instead of silently resharded
            host_params = jax.tree_util.tree_map(np.asarray, out.params)
            if p_sh is not None:
                with pytest.raises(HandoffMismatch):
                    InferenceEngine(model, host_params,
                                    EngineConfig(n_slots=2, max_len=64,
                                                 param_shardings=p_sh))
                from distributed_pytorch_tpu.models.generate import \
                    make_generate_fn
                gen = make_generate_fn(model, 2, param_shardings=p_sh)
                with pytest.raises(HandoffMismatch):
                    gen(host_params,
                        jnp.asarray(rng.integers(0, 64, (1, 4))),
                        jax.random.PRNGKey(0))
        finally:
            if world > 1:
                dist.cleanup()

    def test_chain_world1(self):
        self._chain(1)

    def test_chain_mesh4(self):
        self._chain(4)

    def test_eval_pins_tree_shardings_from_constrained_step(self,
                                                            group8):
        """The constraint-ladder consumer half: a ZeRO-3 step's params
        out-shardings are a TREE; make_eval_step(like=) must pin that
        tree verbatim (a replicated fallback would make pjit silently
        all-gather the sharded weights on entry — the review repro)."""
        from jax.sharding import NamedSharding

        model, params, opt, loss_fn = _setup(hidden=64, in_dim=8)
        batch = _batch(in_dim=8)
        opt_state = opt.init(params)
        p_specs, _, _ = shard_layouts(params, opt_state, n_shards=8,
                                      min_size=64)
        step = make_step(loss_fn, opt, mesh=context.get_mesh(),
                         specs=StepSpecs(params=p_specs), donate=False)
        out = step(params, opt_state, batch)
        pinned = handoff_shardings(step)
        assert not isinstance(pinned, NamedSharding)   # a TREE
        ev = front_door.make_eval_step(
            lambda p, b: model.apply(p, b[0]).argmax(-1), like=step)
        assert ev.in_shardings["params"] is pinned
        # the step's own output feeds it with zero resharding
        verify_handoff(out.params, pinned)
        pred = ev(out.params, batch)
        pred = ev(out.params, batch)
        assert np.asarray(pred).shape == (16,)
        assert ev.trace_counts["n"] == 1

    def test_verify_handoff_surface(self, group8):
        model, params, opt, loss_fn = _setup()
        step = make_step(loss_fn, opt, donate=False)
        sh = handoff_shardings(step)
        assert sh is not None
        with pytest.raises(HandoffMismatch, match="handoff"):
            verify_handoff(params, sh)     # uncommitted host tree
        placed = jax.device_put(params, sh)
        assert verify_handoff(placed, sh) is placed   # zero-copy

    def test_out_equals_in_shardings_every_engine(self, group8):
        """The pjit-to-pjit precondition, asserted on the declared
        contract for the dp and sharded engines (the constraint ladder
        is covered in TestSpecMatrix)."""
        model, params, opt, loss_fn = _setup()
        for kw in ({}, {"weight_update": "sharded"}):
            step = make_step(loss_fn, opt, donate=False, **kw)
            if kw:
                step.init_opt_state(params)
                st = step.init_opt_state(params)
                step(params, st, _batch())   # sharded pins lazily
            assert step.in_shardings["params"] == \
                step.out_shardings["params"]
            assert step.in_shardings["opt"] == step.out_shardings["opt"]
