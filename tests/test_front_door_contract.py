"""Cross-front-door contract: the same collective operations produce the
SAME primary-side results under all three front doors —

1. **SPMD** (single controller, stacked arrays over the dp mesh axis,
   ``distributed_pytorch_tpu.api``),
2. **host** (one OS process per rank over the native TCP group,
   ``runtime.launch_multiprocess`` + the same api), and
3. **torch** (the ``torch_compat/distributed`` shim over the same native
   transport, torch tensors).

One canonical pure-numpy expectation (:func:`canonical`) parameterized by
world size is the oracle; each door's run must match it exactly. This is
the operational form of the reference's semantics table (SURVEY.md §2.1
#12-17): sum and avg all-reduce, rooted reduce, rooted gather, broadcast
from a nonzero src, and the invalid-op ValueError. Non-primary-side
quirks (gather's zeros, reduce's untouched buffers) are pinned separately
per door in tests/test_collectives.py, tests/test_host_backend.py, and
tests/test_torch_compat.py — this file is about the values every door
must AGREE on.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

import distributed_pytorch_tpu as dist

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SHIM_DIR = os.path.join(REPO, "torch_compat")


def rank_tensor(rank: int):
    """The deterministic per-rank payload every door uses."""
    return (rank + 1.0) * np.asarray([1.0, 2.0, 3.0], np.float32)


def canonical(world: int) -> dict:
    """What the API must observably return on the PRIMARY, any door."""
    stack = np.stack([rank_tensor(r) for r in range(world)])
    return {
        "all_reduce_sum": stack.sum(axis=0).tolist(),
        "all_reduce_avg": (stack.sum(axis=0) / world).tolist(),
        "reduce_root": stack.sum(axis=0).tolist(),
        "gather": stack.tolist(),
        "broadcast_src1": stack[min(1, world - 1)].tolist(),
        "invalid_op_raises": True,
    }


def _observe_spmd(world: int) -> dict:
    """SPMD door: stacked (world, ...) arrays on the virtual mesh."""
    import jax.numpy as jnp

    stack = jnp.asarray(np.stack([rank_tensor(r) for r in range(world)]))
    out = {
        "all_reduce_sum": np.asarray(dist.all_reduce(stack, "sum"))[0]
        .tolist(),
        "all_reduce_avg": np.asarray(dist.all_reduce(stack, "avg"))[0]
        .tolist(),
        "reduce_root": np.asarray(dist.reduce(stack, "sum")).tolist(),
        "gather": [np.asarray(g).tolist() for g in dist.gather(stack)],
        "broadcast_src1": np.asarray(dist.broadcast(stack, src=1))[0]
        .tolist(),
    }
    dist.barrier()
    dist.wait_for_everyone()
    try:
        dist.all_reduce(stack, "prod")
        out["invalid_op_raises"] = False
    except ValueError:
        out["invalid_op_raises"] = True
    return out


def _host_worker(rank, world, out_path):
    """Host door: per-rank process, own tensor, native TCP collectives."""
    import numpy as np

    import distributed_pytorch_tpu as dist
    from tests.test_front_door_contract import rank_tensor

    dist.init_process_group(rank, world)
    x = rank_tensor(rank)
    out = {
        "all_reduce_sum": np.asarray(dist.all_reduce(x.copy(), "sum"))
        .tolist(),
        "all_reduce_avg": np.asarray(dist.all_reduce(x.copy(), "avg"))
        .tolist(),
        "reduce_root": np.asarray(dist.reduce(x.copy(), "sum")).tolist(),
        "gather": [np.asarray(g).tolist() for g in dist.gather(x.copy())],
        "broadcast_src1": np.asarray(
            dist.broadcast(x.copy(), src=1)).tolist(),
    }
    dist.barrier()
    dist.wait_for_everyone()
    try:
        dist.all_reduce(x.copy(), "prod")
        out["invalid_op_raises"] = False
    except ValueError:
        out["invalid_op_raises"] = True
    if dist.is_primary():
        with open(out_path, "w") as f:
            json.dump(out, f)
    dist.cleanup()


_TORCH_WORKER = r"""
import json, sys
import numpy as np
import torch
import distributed as dist  # the shim, via PYTHONPATH

rank, world, port, out_path = (int(sys.argv[1]), int(sys.argv[2]),
                               sys.argv[3], sys.argv[4])
import os
os.environ["MASTER_ADDR"] = "localhost"
os.environ["MASTER_PORT"] = port
dist.init_process_group(rank, world)
x0 = (rank + 1.0) * torch.tensor([1.0, 2.0, 3.0])
out = {}
out["all_reduce_sum"] = dist.all_reduce(x0.clone(), "sum").tolist()
out["all_reduce_avg"] = dist.all_reduce(x0.clone(), "avg").tolist()
out["reduce_root"] = dist.reduce(x0.clone(), "sum").tolist()
out["gather"] = [g.tolist() for g in dist.gather(x0.clone())]
b = dist.sync_params([x0.clone()])  # broadcast is from rank 0 in the shim
dist.barrier()
dist.wait_for_everyone()
try:
    dist.all_reduce(x0.clone(), "prod")
    out["invalid_op_raises"] = False
except ValueError:
    out["invalid_op_raises"] = True
if dist.is_primary():
    with open(out_path, "w") as f:
        json.dump(out, f)
dist.cleanup()
"""


class TestFrontDoorContract:
    def test_spmd_door_matches_canonical(self, group8):
        assert _observe_spmd(8) == canonical(8)

    @pytest.mark.slow
    def test_host_door_matches_canonical(self, tmp_path):
        from distributed_pytorch_tpu.runtime import launch_multiprocess

        out_path = str(tmp_path / "host.json")
        launch_multiprocess(_host_worker, 2, out_path)
        with open(out_path) as f:
            got = json.load(f)
        assert got == canonical(2)

    @pytest.mark.slow
    def test_torch_door_matches_canonical(self, tmp_path):
        from distributed_pytorch_tpu.runtime.launcher import find_free_port

        out_path = str(tmp_path / "torch.json")
        port = str(find_free_port())
        env = dict(os.environ, PYTHONPATH=SHIM_DIR)
        procs = [subprocess.Popen(
            [sys.executable, "-c", _TORCH_WORKER, str(r), "2", port,
             out_path],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True) for r in range(2)]
        outs = [p.communicate(timeout=120)[0] for p in procs]
        assert all(p.returncode == 0 for p in procs), "\n".join(outs)
        with open(out_path) as f:
            got = json.load(f)
        want = canonical(2)
        # the shim has no standalone broadcast-with-src (the reference
        # exposes only sync_params' broadcast-from-0); drop that key
        want.pop("broadcast_src1")
        assert got == want

    def test_oracle_self_check(self):
        """Guards the shared oracle with hand-computed constants (each
        door is compared to this oracle in the three tests above — that
        is the cross-door agreement; worlds differ, the oracle is exact
        for every world)."""
        c2 = canonical(2)
        assert c2["all_reduce_sum"] == [3.0, 6.0, 9.0]
        assert c2["reduce_root"] == c2["all_reduce_sum"]
        assert np.allclose(c2["broadcast_src1"], rank_tensor(1))
