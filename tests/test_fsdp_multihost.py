"""FSDP (ZeRO-3 layout) numerics + sharding, and multi-host helpers on the
8-device virtual mesh. FSDP must be a pure layout change: identical loss
trajectory to replicated DP, with params/grads/moments actually sharded."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from distributed_pytorch_tpu import models, optim
from distributed_pytorch_tpu.ops.losses import cross_entropy_per_example
from distributed_pytorch_tpu.parallel import (fsdp_param_specs,
                                              make_fsdp_train_step,
                                              make_spmd_train_step,
                                              shard_batch_spec,
                                              shard_model_and_opt)
from distributed_pytorch_tpu.parallel.fsdp import opt_state_specs
from distributed_pytorch_tpu.parallel.tensor import shard_params
from distributed_pytorch_tpu.runtime import context, multihost


def _mesh8():
    return context.init_mesh(dp=8)


def _lm():
    # dims chosen divisible by 8 so every big leaf shards
    return models.TransformerLM(vocab=64, dim=32, n_layers=2, n_heads=4,
                                max_seq=16)


def _loss_fn(model):
    def loss_fn(p, batch):
        x, y = batch
        return cross_entropy_per_example(model.apply(p, x), y).mean(), {}
    return loss_fn


class TestFsdpSpecs:
    def test_largest_divisible_dim_sharded(self):
        params = {"w": jnp.zeros((48, 64)), "b": jnp.zeros((7,)),
                  "tiny": jnp.zeros((8, 8))}
        specs = fsdp_param_specs(params, 8, min_size=128)
        assert specs["w"] == P(None, "dp")      # 64 is the largest dim % 8
        assert specs["b"] == P()                # 7 not divisible
        assert specs["tiny"] == P()             # below min_size


    def test_base_specs_respected(self):
        params = {"w": jnp.zeros((64, 128))}
        base = {"w": P(None, "tp")}             # tp already owns dim 1
        specs = fsdp_param_specs(params, 8, min_size=1, base_specs=base)
        assert specs["w"] == P("dp", "tp")      # fsdp takes the free dim

    def test_opt_state_specs_adamw(self):
        params = {"w": jnp.zeros((64, 64))}
        p_specs = fsdp_param_specs(params, 8, min_size=1)
        state = optim.adamw(1e-3).init(params)
        o = opt_state_specs(state, p_specs)
        assert o.step == P()
        assert o.mu["w"] == p_specs["w"] and o.nu["w"] == p_specs["w"]


class TestFsdpNumerics:
    @pytest.mark.slow
    def test_matches_replicated_dp(self):
        """ZeRO-3 is a layout, not math: the loss trajectory must equal
        replicated data parallelism step for step."""
        mesh = _mesh8()
        model = _lm()
        loss_fn = _loss_fn(model)
        opt = optim.adamw(1e-3)
        rng = np.random.default_rng(0)
        toks = rng.integers(0, 64, (16, 16)).astype(np.int32)
        batch = shard_batch_spec((toks, toks), mesh, P("dp", None))

        # replicated baseline
        from distributed_pytorch_tpu.parallel import replicated_specs
        p0 = model.init(jax.random.PRNGKey(0))
        p_rep = shard_params(p0, replicated_specs(p0), mesh)
        o_rep = opt.init(p_rep)
        step_rep = make_spmd_train_step(loss_fn, opt, donate=False)

        # fsdp
        params = model.init(jax.random.PRNGKey(0))
        specs = fsdp_param_specs(params, 8, min_size=1)
        opt_state = opt.init(params)
        params, opt_state = shard_model_and_opt(params, opt_state, mesh,
                                                specs)
        step_fsdp = make_fsdp_train_step(loss_fn, opt, mesh, specs,
                                         donate=False)

        for _ in range(3):
            out_r = step_rep(p_rep, o_rep, batch)
            out_f = step_fsdp(params, opt_state, batch)
            p_rep, o_rep = out_r.params, out_r.opt_state
            params, opt_state = out_f.params, out_f.opt_state
            np.testing.assert_allclose(float(out_f.loss), float(out_r.loss),
                                       rtol=1e-5)

    @pytest.mark.slow
    @pytest.mark.parametrize("stage", ["zero1", "zero2"])
    def test_zero_stages_match_replicated_dp_and_shard_state(self, stage):
        """ZeRO-1 (replicated grads) and ZeRO-2 (reduce-scattered grads):
        replicated params + sharded optimizer state are pure layout —
        loss trajectory equals replicated DP; after a step the params
        stay whole per device while the AdamW moments hold 1/8 shards.
        The two rungs differ only in gradient layout (internal to the
        compiled step), so both pin against the same oracle."""
        from distributed_pytorch_tpu.parallel import (make_zero1_train_step,
                                                      make_zero2_train_step,
                                                      replicated_specs)
        from distributed_pytorch_tpu.parallel.fsdp import opt_state_specs
        make_step = {"zero1": make_zero1_train_step,
                     "zero2": make_zero2_train_step}[stage]

        mesh = _mesh8()
        model = _lm()
        loss_fn = _loss_fn(model)
        opt = optim.adamw(1e-3)
        rng = np.random.default_rng(0)
        toks = rng.integers(0, 64, (16, 16)).astype(np.int32)
        batch = shard_batch_spec((toks, toks), mesh, P("dp", None))

        p0 = model.init(jax.random.PRNGKey(0))
        p_rep = shard_params(p0, replicated_specs(p0), mesh)
        o_rep = opt.init(p_rep)
        step_rep = make_spmd_train_step(loss_fn, opt, donate=False)

        params = shard_params(model.init(jax.random.PRNGKey(0)),
                              replicated_specs(p0), mesh)
        step_z, s_specs = make_step(loss_fn, opt, mesh, params,
                                    min_size=1, donate=False)
        o_raw = opt.init(params)
        opt_state = shard_params(
            o_raw, opt_state_specs(o_raw, s_specs, params=params), mesh)

        for _ in range(3):
            out_r = step_rep(p_rep, o_rep, batch)
            out_z = step_z(params, opt_state, batch)
            p_rep, o_rep = out_r.params, out_r.opt_state
            params, opt_state = out_z.params, out_z.opt_state
            np.testing.assert_allclose(float(out_z.loss),
                                       float(out_r.loss), rtol=1e-5)

        w = params["blocks"][0]["fc1"]["w"]
        assert w.addressable_shards[0].data.size == w.size  # replicated
        mu = opt_state.mu["blocks"][0]["fc1"]["w"]
        assert mu.addressable_shards[0].data.size == mu.size // 8

    def test_state_actually_sharded(self):
        mesh = _mesh8()
        model = _lm()
        params = model.init(jax.random.PRNGKey(0))
        specs = fsdp_param_specs(params, 8, min_size=1)
        opt = optim.adamw(1e-3)
        params, opt_state = shard_model_and_opt(params, opt.init(params),
                                                mesh, specs)
        w = params["blocks"][0]["fc1"]["w"]
        assert "dp" in jax.tree_util.tree_leaves(
            [w.sharding.spec])[0] or "dp" in tuple(w.sharding.spec)
        # local shard is 1/8 of the global array
        shard = w.addressable_shards[0].data
        assert shard.size == w.size // 8
        mu = opt_state.mu["blocks"][0]["fc1"]["w"]
        assert mu.addressable_shards[0].data.size == mu.size // 8

        # updated state keeps the sharded layout (no silent re-replication)
        loss_fn = _loss_fn(model)
        rng = np.random.default_rng(1)
        toks = rng.integers(0, 64, (16, 16)).astype(np.int32)
        batch = shard_batch_spec((toks, toks), mesh, P("dp", None))
        out = make_fsdp_train_step(loss_fn, opt, mesh, specs,
                                   donate=False)(params, opt_state, batch)
        w2 = out.params["blocks"][0]["fc1"]["w"]
        assert w2.addressable_shards[0].data.size == w2.size // 8


class TestMultihost:
    def test_single_host_degradation(self):
        multihost.initialize()  # no-op off-pod
        assert multihost.num_hosts() == 1
        assert multihost.host_index() == 0
        assert multihost.is_primary_host()
        start, stop = multihost.local_device_slice()
        assert (start, stop) == (0, len(jax.local_devices()))

    def test_hybrid_mesh_single_host(self):
        mesh = multihost.init_hybrid_mesh(ici=[("dp", 4), ("tp", 2)])
        assert mesh.shape == {"dp": 4, "tp": 2}
        mesh2 = multihost.init_hybrid_mesh(ici=[("dp", 8)],
                                           dcn=[("dp_outer", 1)])
        assert mesh2.shape == {"dp_outer": 1, "dp": 8}

    def test_hybrid_mesh_size_mismatch_raises(self):
        with pytest.raises(ValueError, match="devices"):
            multihost.init_hybrid_mesh(ici=[("dp", 4)])

    def test_hybrid_mesh_usable_for_compute(self):
        mesh = multihost.init_hybrid_mesh(ici=[("dp", 8)])
        x = jnp.arange(16.0)
        y = jax.jit(
            lambda x: x * 2,
            in_shardings=jax.NamedSharding(mesh, P("dp")),
            out_shardings=jax.NamedSharding(mesh, P("dp")))(x)
        np.testing.assert_allclose(np.asarray(y), np.arange(16.0) * 2)

    def test_control_plane_helpers(self):
        g = multihost.process_allgather(np.array([1.5, 2.5]))
        assert g.shape == (1, 2)
        b = multihost.broadcast_from_primary(np.array([3]))
        np.testing.assert_array_equal(b, [3])


@functools.lru_cache(maxsize=1)
def _dcn_capability():
    """Probe whether THIS environment can form real cross-process DCN
    device visibility (two jax.distributed processes whose jax.devices()
    span both hosts). Some CI/dev containers rendezvous fine but never
    merge device views — the full test would fail on an environment
    limitation, not a code bug, so the tier-1 gate skips with the
    probe's reason instead (ISSUE 5 satellite). Returns a tri-state
    verdict: ``capable`` / ``incapable`` (the worker's deliberate exit
    31) / ``broken`` (any other crash — the gate FAILS on those rather
    than hiding a real regression behind a skip). Cached per session:
    the probe costs two jax startups."""
    import os
    import subprocess
    import sys as _sys

    from distributed_pytorch_tpu.runtime.launcher import find_free_port

    # _multihost_worker.PROBE_INCAPABLE — referenced by value: importing
    # the worker module would run its XLA_FLAGS scrub and platform switch
    # inside THIS test process
    PROBE_INCAPABLE = 31

    here = os.path.dirname(os.path.abspath(__file__))
    worker = os.path.join(here, "_multihost_worker.py")
    coord = f"127.0.0.1:{find_free_port()}"
    procs = [subprocess.Popen(
        [_sys.executable, worker, "--probe", coord, "2", str(i)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
        for i in range(2)]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=120)
            outs.append(out.strip())
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        # a hung probe is NOT the worker's deliberate incapable verdict:
        # localhost rendezvous answers in seconds when healthy, so a
        # deadlock here is a regression signal and must fail, not skip
        return "broken", ("DCN probe hung (jax.distributed rendezvous "
                          "deadlocked past 120s)")
    codes = [p.returncode for p in procs]
    if all(rc == 0 for rc in codes):
        return "capable", ""
    if all(rc in (0, PROBE_INCAPABLE) for rc in codes):
        # the worker's deliberate verdict, not a crash: skippable
        return "incapable", ("real cross-process DCN unavailable in this "
                             "environment: " + "; ".join(outs))
    # any OTHER exit means the probe itself broke (an import error, a
    # regression in multihost.initialize) — that must FAIL tier-1, not
    # silently skip it
    return "broken", (f"DCN probe crashed (exit codes {codes}): "
                      + "; ".join(outs))


class TestRealMultiProcess:
    def test_two_process_dcn_step(self):
        """REAL multi-process jax.distributed: two OS processes with a
        local coordinator, 4 CPU devices each -> 8 global devices;
        asserts process_count()==2 and runs a gradient-averaging DP step
        whose collective crosses the process boundary, plus the
        control-plane allgather/broadcast helpers. (The reference cannot
        do any of this: its rendezvous is hardcoded localhost-single-node,
        reference distributed.py:48.) Workers run tests/_multihost_worker.py
        in fresh subprocesses — platform selection must precede backend
        init, so this cannot run in-process. Gated on a capability probe:
        environments that cannot merge device views across processes
        SKIP with the probe's reason rather than failing tier-1."""
        import os
        import subprocess
        import sys as _sys

        from distributed_pytorch_tpu.runtime.launcher import find_free_port

        verdict, reason = _dcn_capability()
        if verdict == "broken":
            pytest.fail(reason)
        if verdict == "incapable":
            pytest.skip(reason)
        here = os.path.dirname(os.path.abspath(__file__))
        worker = os.path.join(here, "_multihost_worker.py")
        coord = f"127.0.0.1:{find_free_port()}"
        procs = [
            subprocess.Popen(
                [_sys.executable, worker, coord, "2", str(i)],
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
            for i in range(2)
        ]
        outs = []
        try:
            for p in procs:
                out, _ = p.communicate(timeout=240)
                outs.append(out)
        except subprocess.TimeoutExpired:
            for p in procs:
                p.kill()
            pytest.fail(f"multi-process workers hung; partial: {outs}")
        assert all(p.returncode == 0 for p in procs), "\n".join(outs)
        assert any("proc 0 ok" in o for o in outs)
        assert any("proc 1 ok" in o for o in outs)


def test_fsdp_shards_master_f32_and_accum_states():
    """Composed optimizer wrappers (master-f32, accumulation) must keep
    their param-sized buffers FSDP-sharded, not silently replicated."""
    mesh = _mesh8()
    try:
        from distributed_pytorch_tpu.optim import (accumulate, adamw,
                                                   constant,
                                                   with_master_f32,
                                                   with_schedule)

        params = {"w": jnp.zeros((64, 64), jnp.bfloat16)}
        specs = fsdp_param_specs(params, 8, min_size=16)
        opt = accumulate(with_master_f32(adamw(1e-3)), 2)
        state = opt.init(params)
        s = opt_state_specs(state, specs)
        # acc buffer, master copy, and both moments all carry the param spec
        assert s.acc == specs
        assert s.inner.master == specs
        assert s.inner.inner.mu == specs and s.inner.inner.nu == specs
        assert s.count == P() and s.inner.inner.step == P()

        # scheduled optimizers shard their inner moments too
        opt2 = with_schedule(adamw, constant(1e-3))
        s2 = opt_state_specs(opt2.init(params), specs)
        assert s2.inner.mu == specs and s2.inner.nu == specs
        assert s2.step == P()
    finally:
        import distributed_pytorch_tpu as dist
        dist.cleanup()


def test_fsdp_fused_ce_matches_unfused(group8):
    """fused_linear_cross_entropy under FSDP: the head weight reaches the
    loss as a dp-sharded leaf; the chunked scan must produce the same
    loss as the materialized-logits path and train."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from distributed_pytorch_tpu import models, optim
    from distributed_pytorch_tpu.ops.losses import (
        cross_entropy, fused_linear_cross_entropy)
    from distributed_pytorch_tpu.parallel.fsdp import (
        fsdp_param_specs, make_fsdp_train_step, shard_model_and_opt)
    from distributed_pytorch_tpu.parallel.spmd import shard_batch_spec
    from distributed_pytorch_tpu.runtime import context
    from jax.sharding import PartitionSpec as P

    model = models.TransformerLM(vocab=64, dim=32, n_layers=2, n_heads=4,
                                 max_seq=16)
    params0 = model.init(jax.random.PRNGKey(0))
    opt = optim.adamw(1e-3)
    mesh = context.get_mesh()
    specs = fsdp_param_specs(params0, 8, min_size=64)
    params, opt_state = shard_model_and_opt(params0, opt.init(params0),
                                            mesh, specs)

    def loss_fused(p, batch):
        toks = batch
        hid = model.apply(p, toks[:, :-1], return_hidden=True)
        return fused_linear_cross_entropy(hid, p["head"]["w"],
                                          toks[:, 1:], chunk_rows=16), {}

    toks = np.random.default_rng(0).integers(0, 64, (8, 17)).astype(np.int32)
    # reference BEFORE the donating step consumes the shared buffers
    ref = float(cross_entropy(
        model.apply(params0, jnp.asarray(toks[:, :-1])),
        jnp.asarray(toks[:, 1:])))
    step = make_fsdp_train_step(loss_fused, opt, mesh, specs)
    batch = shard_batch_spec(toks, mesh, P("dp", None))
    out = step(params, opt_state, batch)
    np.testing.assert_allclose(float(out.loss), ref, rtol=2e-5)

    l0 = float(out.loss)
    for _ in range(3):
        out = step(out.params, out.opt_state, batch)
    assert float(out.loss) < l0


def test_opt_state_specs_adamw_8bit_codes_shard():
    """adamw_8bit's quantized moments shard under the FSDP layout: the
    param-shaped int8 code arrays inherit the param specs, per-block
    scales replicate — the '8-bit on top of ZeRO' composition is a real
    sharding, not a silent P() fallback."""
    params = {"w": jnp.zeros((64, 64), jnp.float32)}
    p_specs = fsdp_param_specs(params, 8, min_size=1)
    state = optim.adamw_8bit(1e-3).init(params)
    o = opt_state_specs(state, p_specs, params=params)
    assert o.step == P()
    assert o.mu["w"].q == p_specs["w"]
    assert o.nu["w"].q == p_specs["w"]
    assert o.mu["w"].scale == P()
    assert o.nu["w"].mid == P()
