"""Grouped-query attention (GQA): n_kv_heads < n_heads, each kv head
serving a group of query heads. Reference semantics: identical to MHA
with every kv head repeated group-size times — checked here against that
repeat for the dense path, the flash kernel (values and all three
gradients — the kernel reads grouped kv via BlockSpec index maps and
group-sums per-q-head dK/dV partials), the module/model plumbing, and
the cached decode path (whose KV cache shrinks by the group factor)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_pytorch_tpu import models
from distributed_pytorch_tpu.models.generate import (init_cache,
                                                     make_generate_fn)
from distributed_pytorch_tpu.nn.attention import (MultiHeadAttention,
                                                  dense_attention)
from distributed_pytorch_tpu.ops import flash_attention


def _qkv(b=2, h=8, h_kv=2, s=24, d=16, seed=0, dtype=jnp.float32):
    kq, kk, kv = jax.random.split(jax.random.PRNGKey(seed), 3)
    q = jax.random.normal(kq, (b, h, s, d), dtype)
    k = jax.random.normal(kk, (b, h_kv, s, d), dtype)
    v = jax.random.normal(kv, (b, h_kv, s, d), dtype)
    return q, k, v


def _repeat_kv(t, group):
    return jnp.repeat(t, group, axis=1)


class TestDenseGQA:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_repeated_kv(self, causal):
        q, k, v = _qkv()
        g = q.shape[1] // k.shape[1]
        got = dense_attention(q, k, v, causal=causal)
        want = dense_attention(q, _repeat_kv(k, g), _repeat_kv(v, g),
                               causal=causal)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=1e-6)

    def test_indivisible_heads_rejected(self):
        q, k, v = _qkv(h=6, h_kv=4)
        with pytest.raises(ValueError, match="divisible"):
            dense_attention(q, k, v)


class TestFlashGQA:
    @pytest.mark.parametrize("causal", [False, True])
    def test_values_match_dense(self, causal):
        q, k, v = _qkv()
        got = flash_attention(q, k, v, causal=causal, block_q=16, block_k=16)
        want = dense_attention(q, k, v, causal=causal)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=2e-5)

    @pytest.mark.parametrize("causal", [False, True])
    def test_grads_match_dense(self, causal):
        """dQ per q-head; dK/dV must be the group-sum over the q-heads
        each kv head serves."""
        q, k, v = _qkv(s=20)

        def lf(q, k, v):
            return jnp.sum(flash_attention(q, k, v, causal=causal,
                                           block_q=16, block_k=16) ** 2)

        def ld(q, k, v):
            return jnp.sum(dense_attention(q, k, v, causal=causal) ** 2)

        got = jax.grad(lf, argnums=(0, 1, 2))(q, k, v)
        want = jax.grad(ld, argnums=(0, 1, 2))(q, k, v)
        for name, a, b in zip("qkv", got, want):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=5e-5, err_msg=f"d{name}")


class TestGQAModule:
    def test_param_shapes_shrink(self):
        mha = MultiHeadAttention(32, 8)
        gqa = MultiHeadAttention(32, 8, n_kv_heads=2)
        p_m = mha.init(jax.random.PRNGKey(0))
        p_g = gqa.init(jax.random.PRNGKey(0))
        assert p_m["qkv"]["w"].shape == (32, 96)     # D + 2D
        assert p_g["qkv"]["w"].shape == (32, 48)     # D + 2*(Hkv*Dh)=D/2

    def test_projection_head_counts(self):
        gqa = MultiHeadAttention(32, 8, n_kv_heads=2)
        p = gqa.init(jax.random.PRNGKey(0))
        q, k, v = gqa.project_qkv(p, jnp.ones((2, 5, 32)))
        assert q.shape == (2, 8, 5, 4)
        assert k.shape == v.shape == (2, 2, 5, 4)

    def test_indivisible_rejected(self):
        with pytest.raises(ValueError, match="n_kv_heads"):
            MultiHeadAttention(32, 8, n_kv_heads=3)


class TestGQAModel:
    def _model(self, **kw):
        return models.TransformerLM(vocab=61, dim=32, n_layers=2, n_heads=4,
                                    n_kv_heads=2, max_seq=64, **kw)

    def test_trains(self):
        from distributed_pytorch_tpu import optim
        from distributed_pytorch_tpu.ops.losses import cross_entropy
        from distributed_pytorch_tpu.parallel import make_train_step
        model = self._model()
        params = model.init(jax.random.PRNGKey(0))
        toks = jax.random.randint(jax.random.PRNGKey(1), (2, 17), 0, 61)

        def loss_fn(p, t):
            return cross_entropy(model.apply(p, t[:, :-1]), t[:, 1:]), {}

        opt = optim.adamw(1e-3)
        step = make_train_step(loss_fn, opt, donate=False)
        out = step(params, opt.init(params), toks)
        l0 = float(out.loss.mean())
        for _ in range(5):
            out = step(out.params, out.opt_state, toks)
        assert float(out.loss.mean()) < l0

    @pytest.mark.slow
    def test_cache_shrinks_and_decode_matches_full_forward(self):
        """The KV cache allocates n_kv_heads; greedy cached decode equals
        argmax over the full uncached forward — the decode einsum's
        grouped-head path against the training path."""
        model = self._model()
        params = model.init(jax.random.PRNGKey(0))
        cache = init_cache(model, batch=2, max_len=16)
        assert cache.k[0].shape == (2, 2, 16, 8)     # Hkv=2, Dh=8

        prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 7), 0, 61)
        out = np.asarray(make_generate_fn(model, 6)(
            params, prompt, jax.random.PRNGKey(2)))
        toks = np.asarray(prompt)
        want = []
        for _ in range(6):
            logits = model.apply(params, jnp.asarray(toks))
            nxt = np.asarray(jnp.argmax(logits[:, -1], axis=-1))
            want.append(nxt)
            toks = np.concatenate([toks, nxt[:, None]], axis=1)
        np.testing.assert_array_equal(out, np.stack(want, axis=1))

    def test_flash_gqa_model_matches_dense_gqa_model(self):
        from distributed_pytorch_tpu.ops import make_flash_attn_fn
        dense = self._model()
        flash = self._model(attn_fn=make_flash_attn_fn(16, 16, min_seq_flash=None))
        params = dense.init(jax.random.PRNGKey(0))
        toks = jax.random.randint(jax.random.PRNGKey(3), (2, 12), 0, 61)
        a = dense.apply(params, toks)
        b = flash.apply(params, toks)
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=3e-5)


def test_window_clamps_default_k_block():
    """Adaptive defaults must not pick k tiles far wider than a sliding
    window's band (that would degrade O(S*W) back toward O(S*block_k))."""
    from distributed_pytorch_tpu.ops.flash_attention import _block_sizes
    bq, bk = _block_sizes(4096, 4096, None, None, d=64, window=128)
    assert bk <= 256
    bq2, bk2 = _block_sizes(4096, 4096, None, None, d=64)
    assert bk2 == 1024 and bq == bq2
    # explicit ints always win
    assert _block_sizes(4096, 4096, 64, 64, d=64, window=128) == (64, 64)


@pytest.mark.slow
def test_moe_lm_gqa_rope_trains():
    """MoETransformerLM accepts n_kv_heads + pos='rope' (no pos table in
    the tree) and its loss decreases."""
    from distributed_pytorch_tpu import optim
    from distributed_pytorch_tpu.models.moe_lm import MoETransformerLM
    from distributed_pytorch_tpu.ops.losses import cross_entropy
    model = MoETransformerLM(vocab=61, dim=32, n_layers=2, n_heads=4,
                             n_experts=2, max_seq=32, n_kv_heads=2,
                             pos="rope")
    params = model.init(jax.random.PRNGKey(0))
    assert "pos" not in params
    toks = jax.random.randint(jax.random.PRNGKey(1), (2, 17), 0, 61)

    def loss_fn(p, t):
        logits, aux = model.apply(p, t[:, :-1])
        return cross_entropy(logits, t[:, 1:]) + 0.01 * aux

    opt = optim.adamw(1e-3)
    opt_state = opt.init(params)
    l0 = None
    for _ in range(6):
        loss, grads = jax.value_and_grad(loss_fn)(params, toks)
        params, opt_state = opt.update(grads, opt_state, params)
        l0 = float(loss) if l0 is None else l0
    assert float(loss) < l0


def test_window_gqa_compose():
    """Sliding-window + GQA together: flash matches dense for a banded
    causal mask with grouped kv heads."""
    from distributed_pytorch_tpu.nn.attention import dense_attention
    q, k, v = _qkv(h=4, h_kv=2, s=32, d=8)
    got = flash_attention(q, k, v, causal=True, window=8,
                          block_q=8, block_k=8)
    want = dense_attention(q, k, v, causal=True, window=8)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-5)
