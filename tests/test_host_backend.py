"""Native host process group: true multi-process collectives (SURVEY.md §4
'multi-process CPU tests') — ring allreduce, rooted reduce/gather (incl.
the zeros-on-non-primary gather contract), broadcast, barrier ordering,
and spawn error propagation (the join=True contract)."""

import multiprocessing as mp
import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from distributed_pytorch_tpu.runtime.multiprocess import launch_multiprocess

WORLD = 4


def _collectives_worker(rank, world, q):
    """Runs in a spawned process: exercises every collective through the
    public API (init_process_group routes to the native group via
    DPX_BACKEND=host set by the launcher)."""
    import numpy as np
    import distributed_pytorch_tpu as dist

    dist.init_process_group(rank, world)
    try:
        assert dist.get_rank() == rank
        assert dist.get_world_size() == world
        assert dist.is_primary() == (rank == 0)
        assert dist.get_backend() == "host"

        # all_reduce sum + avg (ring)
        x = np.full((5,), float(rank + 1), np.float32)
        s = dist.all_reduce(x.copy(), op="sum")
        a = dist.all_reduce(x.copy(), op="avg")

        # big buffer: crosses socket-buffer sizes (deadlock regression)
        big = np.full((300_000,), float(rank + 1), np.float32)
        bigsum = dist.all_reduce(big, op="sum")

        # rooted reduce: only rank 0 must hold the sum
        r = dist.reduce(np.full((3,), float(rank + 1), np.float32))

        # rooted gather: zeros on non-primary (reference wart, exact)
        g = dist.gather(np.full((2,), float(rank), np.float32))

        # all_gather: every rank sees the stacked values
        ag = dist.all_gather(np.full((2,), float(rank), np.float32))

        # max all_reduce (SPMD-parity extension)
        mx = dist.all_reduce(np.full((2,), float(rank), np.float32), op="max")

        # integer reduce must preserve dtype exactly
        ir = dist.reduce(np.full((2,), rank + 1, np.int64))

        # broadcast from rank 2
        b = dist.broadcast(np.full((4,), float(rank), np.float32), src=2)

        # sync_params from rank 0
        p = dist.sync_params([np.full((2,), float(rank), np.float32)])[0]

        dist.barrier()
        dist.wait_for_everyone()

        q.put((rank, {
            "sum": s.tolist(), "avg": a.tolist(),
            "bigsum0": float(bigsum[0]), "bigsum_last": float(bigsum[-1]),
            "reduce": r.tolist(),
            "gather": [t.tolist() for t in g],
            "all_gather": np.asarray(ag).tolist(),
            "max": mx.tolist(),
            "int_reduce": ir.tolist(), "int_reduce_dtype": str(ir.dtype),
            "bcast": b.tolist(), "sync": p.tolist(),
        }))
    finally:
        dist.cleanup()


@pytest.mark.slow
def test_native_collectives_multiprocess():
    ctx = mp.get_context("spawn")
    q = ctx.Queue()
    launch_multiprocess(_collectives_worker, WORLD, q)
    results = {}
    while len(results) < WORLD:
        rank, data = q.get(timeout=60)
        results[rank] = data

    expect_sum = float(sum(range(1, WORLD + 1)))
    for rank in range(WORLD):
        d = results[rank]
        assert d["sum"] == [expect_sum] * 5
        assert d["avg"] == [expect_sum / WORLD] * 5
        assert d["bigsum0"] == expect_sum and d["bigsum_last"] == expect_sum
        assert d["bcast"] == [2.0] * 4          # src rank 2's value
        assert d["sync"] == [0.0, 0.0]           # rank 0's value
        assert d["all_gather"] == [[float(r)] * 2 for r in range(WORLD)]
        assert d["max"] == [float(WORLD - 1)] * 2
        assert d["int_reduce_dtype"] == "int64"
        if rank == 0:
            assert d["int_reduce"] == [int(expect_sum)] * 2
        else:
            assert d["int_reduce"] == [rank + 1] * 2
        if rank == 0:
            assert d["reduce"] == [expect_sum] * 3
            assert d["gather"] == [[float(r)] * 2 for r in range(WORLD)]
        else:
            # non-root reduce buffer unchanged; gather list all zeros
            assert d["reduce"] == [float(rank + 1)] * 3
            assert d["gather"] == [[0.0, 0.0] for _ in range(WORLD)]


def _quant_ring_worker(rank, world, q, n):
    """Native quantized ring (dpx_allreduce_q8) through the public API:
    result digests prove cross-rank bit-determinism and bit-parity with
    the numpy executable spec (comm/wire.py:simulate_quant_ring); comm
    stats prove the wire moved ~4x fewer bytes."""
    import hashlib

    import numpy as np
    import distributed_pytorch_tpu as dist
    from distributed_pytorch_tpu.comm import collectives
    from distributed_pytorch_tpu.runtime import context

    dist.init_process_group(rank, world)
    comm = context.get_host_comm()
    try:
        x = (np.random.default_rng(rank).standard_normal(n) * 2
             ).astype(np.float32)
        out = collectives.all_reduce(x, op="sum", wire="quant")
        # sync_params over the quantized wire: bit-identical everywhere
        p = collectives.sync_params(
            [np.random.default_rng(100 + rank).standard_normal(2048)
             .astype(np.float32)], wire="quant")[0]
        q.put((rank,
               hashlib.sha256(np.ascontiguousarray(out).tobytes())
               .hexdigest(),
               hashlib.sha256(np.ascontiguousarray(p).tobytes())
               .hexdigest(),
               comm.stats.summary().get("allreduce_q8", {}).get("bytes")))
    finally:
        dist.cleanup()


@pytest.mark.slow
def test_native_quant_ring_determinism_and_parity():
    import hashlib

    from distributed_pytorch_tpu.comm import wire

    n = 70000  # ragged: not a block or world multiple
    ctx = mp.get_context("spawn")
    q = ctx.Queue()
    launch_multiprocess(_quant_ring_worker, WORLD, q, n)
    res = {}
    while len(res) < WORLD:
        rank, d, pd, qbytes = q.get(timeout=60)
        res[rank] = (d, pd, qbytes)
    # bit-identical across ranks (allreduce AND quant param sync)
    assert len({v[0] for v in res.values()}) == 1
    assert len({v[1] for v in res.values()}) == 1
    # bit-identical to the numpy executable spec
    xs = [(np.random.default_rng(r).standard_normal(n) * 2
           ).astype(np.float32) for r in range(WORLD)]
    sim, sim_bytes = wire.simulate_quant_ring(xs)
    assert (hashlib.sha256(sim[0].tobytes()).hexdigest()
            == res[0][0])
    # recorded wire bytes match the accounting (per-rank share)
    assert res[0][2] == sim_bytes // WORLD


def _failing_worker(rank, world):
    import distributed_pytorch_tpu as dist
    dist.init_process_group(rank, world)
    try:
        if rank == 1:
            raise RuntimeError("boom on rank 1")
        dist.barrier()  # others would wait; rank 1 dies first
    finally:
        dist.cleanup()


def test_spawn_propagates_child_failure():
    """join=True contract (reference distributed.py:51-52): a failing
    child surfaces in the parent as an exception naming the rank."""
    with pytest.raises(RuntimeError, match="rank 1"):
        launch_multiprocess(_failing_worker, 2)


def _invalid_op_worker(rank, world):
    import numpy as np
    import distributed_pytorch_tpu as dist
    dist.init_process_group(rank, world)
    try:
        try:
            dist.all_reduce(np.ones(2, np.float32), op="product")
        except ValueError:
            return  # expected — reference distributed.py:131
        raise AssertionError("invalid op did not raise")
    finally:
        dist.cleanup()


def test_invalid_op_raises_in_host_mode():
    launch_multiprocess(_invalid_op_worker, 2)


def _ddp_worker(rank, world, q):
    """Fixed global batch split across ranks; host-mode DDP step (native
    bucketed grad allreduce). Reports the loss trajectory."""
    import jax
    import numpy as np
    import distributed_pytorch_tpu as dist
    from distributed_pytorch_tpu import models, optim
    from distributed_pytorch_tpu.ops.losses import cross_entropy_per_example
    from distributed_pytorch_tpu.parallel import make_train_step

    if world > 1:
        dist.init_process_group(rank, world)
    try:
        model = models.DummyModel(in_dim=1, hidden_dim=8, n_classes=4)
        params = model.init(jax.random.PRNGKey(0))
        opt = optim.adamw(1e-2)
        opt_state = opt.init(params)

        def loss_fn(p, batch):
            x, y = batch
            logits = model.apply(p, x)
            return cross_entropy_per_example(logits, y).mean(), {}

        step = make_train_step(loss_fn, opt)
        rng = np.random.default_rng(0)
        losses = []
        for _ in range(4):
            x = rng.random((8, 1), dtype=np.float32)
            y = rng.integers(0, 4, (8,)).astype(np.int32)
            lo = rank * (8 // max(world, 1))
            hi = lo + (8 // max(world, 1))
            out = step(params, opt_state, (x[lo:hi], y[lo:hi]))
            params, opt_state = out.params, out.opt_state
            # global mean loss = avg of per-rank means (equal shards)
            l = dist.all_reduce(
                np.asarray(out.loss, np.float32), op="avg") \
                if world > 1 else np.asarray(out.loss)
            losses.append(float(np.asarray(l).reshape(-1)[0]))
        q.put((rank, losses))
    finally:
        dist.cleanup()


@pytest.mark.slow
def test_host_ddp_loss_parity_vs_single_process():
    """2-process native-DDP training reproduces the single-process loss
    trajectory on the same global batches (BASELINE loss-curve parity,
    host front door)."""
    ctx = mp.get_context("spawn")
    q = ctx.Queue()
    launch_multiprocess(_ddp_worker, 1, q)
    _, ref = q.get(timeout=60)

    q2 = ctx.Queue()
    launch_multiprocess(_ddp_worker, 2, q2)
    results = {}
    while len(results) < 2:
        rank, losses = q2.get(timeout=60)
        results[rank] = losses

    np.testing.assert_allclose(results[0], results[1], rtol=1e-6)
    np.testing.assert_allclose(ref, results[0], rtol=2e-5, atol=1e-6)


def _env_reporter(rank, world, out_dir):
    import json
    import os
    with open(os.path.join(out_dir, f"rank{rank}.json"), "w") as f:
        json.dump({"JAX_PLATFORMS": os.environ.get("JAX_PLATFORMS"),
                   "TPU_VISIBLE_DEVICES":
                       os.environ.get("TPU_VISIBLE_DEVICES")}, f)


class TestPerRankDeviceAssignment:
    def test_default_children_are_cpu(self, tmp_path):
        import json

        from distributed_pytorch_tpu.runtime import launch_multiprocess

        launch_multiprocess(_env_reporter, 2, str(tmp_path))
        for r in range(2):
            with open(tmp_path / f"rank{r}.json") as f:
                env = json.load(f)
            # JAX_PLATFORMS=cpu is what keeps children off the chip;
            # TPU_VISIBLE_DEVICES is deliberately left alone (ambient)
            assert env["JAX_PLATFORMS"] == "cpu"

    def test_accel_optin_assigns_chip_per_rank(self, tmp_path, monkeypatch):
        """DPX_MULTIPROC_ACCEL=tpu: rank r's child owns chip r (the
        torch one-process-per-device model; reference rank->device
        mapping, distributed.py:88-91). Plumbing contract only — this
        host has one chip, so the env is asserted, not the execution."""
        import json

        from distributed_pytorch_tpu.runtime import launch_multiprocess
        from distributed_pytorch_tpu.runtime.multiprocess import (
            MULTIPROC_ACCEL_ENV)

        monkeypatch.setenv(MULTIPROC_ACCEL_ENV, "tpu")
        launch_multiprocess(_env_reporter, 2, str(tmp_path))
        for r in range(2):
            with open(tmp_path / f"rank{r}.json") as f:
                env = json.load(f)
            assert env["JAX_PLATFORMS"] == "tpu"
            assert env["TPU_VISIBLE_DEVICES"] == str(r)


    def test_unknown_accel_value_raises(self, monkeypatch):
        from distributed_pytorch_tpu.runtime import launch_multiprocess
        from distributed_pytorch_tpu.runtime.multiprocess import (
            MULTIPROC_ACCEL_ENV)

        monkeypatch.setenv(MULTIPROC_ACCEL_ENV, "gpu")
        with pytest.raises(ValueError, match="not supported"):
            launch_multiprocess(_env_reporter, 2, "/tmp")
