"""Model-zoo tests: shapes, trainability on the 8-device mesh, and the
stateful (BatchNorm) + scan-fused training paths."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import distributed_pytorch_tpu as dist
from distributed_pytorch_tpu import models, optim
from distributed_pytorch_tpu.runtime.jax_compat import shard_map
from distributed_pytorch_tpu.ops.losses import (cross_entropy,
                                                cross_entropy_per_example)
from distributed_pytorch_tpu.parallel import (make_scan_train_steps,
                                              make_stateful_train_step,
                                              make_train_step, stack_state)


def test_transformer_lm_shapes():
    model = models.TransformerLM(vocab=64, dim=32, n_layers=2, n_heads=4,
                                 max_seq=16)
    params = model.init(jax.random.PRNGKey(0))
    tokens = jnp.zeros((2, 16), jnp.int32)
    logits = model.apply(params, tokens)
    assert logits.shape == (2, 16, 64)


def test_transformer_causality():
    """Changing a future token must not change past logits."""
    model = models.TransformerLM(vocab=64, dim=32, n_layers=2, n_heads=4,
                                 max_seq=8)
    params = model.init(jax.random.PRNGKey(0))
    a = jnp.array([[1, 2, 3, 4, 5, 6, 7, 8]], jnp.int32)
    b = a.at[0, 6].set(9)
    la = model.apply(params, a)
    lb = model.apply(params, b)
    np.testing.assert_allclose(np.asarray(la[0, :6]), np.asarray(lb[0, :6]),
                               rtol=1e-5)
    assert not np.allclose(np.asarray(la[0, 6:]), np.asarray(lb[0, 6:]))


def test_transformer_dp_training(group8):
    model = models.TransformerLM(vocab=32, dim=32, n_layers=1, n_heads=2,
                                 max_seq=8)
    params = dist.replicate(model.init(jax.random.PRNGKey(0)))
    opt = optim.adamw(1e-3)
    opt_state = dist.replicate(opt.init(params))

    def loss_fn(p, batch):
        x, y = batch
        logits = model.apply(p, x)
        per_tok = cross_entropy_per_example(logits, y)
        return per_tok.mean(), {"per_tok": per_tok.mean(axis=-1)}

    step = make_train_step(loss_fn, opt)
    rng = np.random.default_rng(0)
    losses = []
    for _ in range(5):
        x = rng.integers(0, 32, (16, 8)).astype(np.int32)
        batch = dist.shard_batch((x[:, :], x[:, :]))
        params, opt_state, loss, _ = step(params, opt_state, batch)
        losses.append(float(np.asarray(loss).mean()))
    assert losses[-1] < losses[0]


def test_resnet18_shapes_and_state():
    model = models.ResNet18(n_classes=10, small_input=True)
    params, state = model.init(jax.random.PRNGKey(0))
    x = jnp.ones((2, 32, 32, 3))
    logits, new_state = model.apply(params, x, state=state, train=True)
    assert logits.shape == (2, 10)
    # running stats must move in train mode
    assert not np.allclose(np.asarray(new_state["bn_stem"]["mean"]),
                           np.asarray(state["bn_stem"]["mean"]))
    # eval mode: state passes through unchanged
    _, eval_state = model.apply(params, x, state=new_state, train=False)
    np.testing.assert_array_equal(np.asarray(eval_state["bn_stem"]["mean"]),
                                  np.asarray(new_state["bn_stem"]["mean"]))


@pytest.mark.slow
def test_resnet18_stateful_dp_training(group8):
    model = models.ResNet18(n_classes=4, small_input=True)
    params, state0 = model.init(jax.random.PRNGKey(0))
    params = dist.replicate(params)
    state = stack_state(state0)  # per-rank BN stats, stacked layout
    opt = optim.adamw(1e-3)
    opt_state = dist.replicate(opt.init(params))

    def loss_fn(p, s, batch):
        x, y = batch
        logits, ns = model.apply(p, x, state=s, train=True)
        per_ex = cross_entropy_per_example(logits, y)
        return per_ex.mean(), (ns, {"correct": jnp.argmax(logits, -1) == y})

    step = make_stateful_train_step(loss_fn, opt)
    rng = np.random.default_rng(0)
    # fixed batch: loss must fall as the model fits it
    x = rng.random((16, 8, 8, 3), dtype=np.float32)
    y = rng.integers(0, 4, (16,)).astype(np.int32)
    losses = []
    for _ in range(4):
        out = step(params, state, opt_state, dist.shard_batch((x, y)))
        params, state, opt_state = out.params, out.state, out.opt_state
        losses.append(float(np.asarray(out.loss).mean()))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]
    # BN state is per-rank: leading axis = world
    leaf = jax.tree_util.tree_leaves(state)[0]
    assert leaf.shape[0] == 8


def test_scan_fused_steps_match_per_step(group8):
    """n scan-fused steps must produce the same params as n individual
    steps (the fast path is numerically the same program)."""
    model = models.DummyModel(in_dim=1, hidden_dim=8, n_classes=4)
    p0 = dist.replicate(model.init(jax.random.PRNGKey(0)))
    opt = optim.adamw(1e-2)
    o0 = dist.replicate(opt.init(p0))

    def loss_fn(p, batch):
        x, y = batch
        logits = model.apply(p, x)
        return cross_entropy(logits, y), {}

    rng = np.random.default_rng(0)
    xs = rng.random((4, 16, 1), dtype=np.float32)
    ys = rng.integers(0, 4, (4, 16)).astype(np.int32)

    step = make_train_step(loss_fn, opt, donate=False)
    p, o = p0, o0
    for t in range(4):
        p, o, _, _ = step(p, o, dist.shard_batch((xs[t], ys[t])))

    run = make_scan_train_steps(loss_fn, opt, n_steps=4, donate=False)
    p2, o2, losses = run(p0, o0, (jnp.asarray(xs), jnp.asarray(ys)))
    assert losses.shape == (4, 8)
    np.testing.assert_allclose(np.asarray(p["lin1"]["w"]),
                               np.asarray(p2["lin1"]["w"]), rtol=1e-5)


@pytest.mark.slow
def test_transformer_remat_same_values_and_grads():
    """remat=True must be numerically invisible (same logits, same grads)
    and actually install the checkpoint primitive. (The HBM saving shows
    on TPU; XLA-CPU's buffer assignment reports identical temp peaks, so
    here the mechanism is pinned via the jaxpr and the peak is only
    required not to regress.)"""
    from distributed_pytorch_tpu.ops.losses import cross_entropy
    from distributed_pytorch_tpu.utils import profiler

    # big enough that per-block activations dominate the temp buffers
    # (at toy sizes checkpoint bookkeeping outweighs the savings)
    kw = dict(vocab=64, dim=128, n_layers=6, n_heads=4, max_seq=128)
    m0 = models.TransformerLM(**kw)
    m1 = models.TransformerLM(remat=True, **kw)
    params = m0.init(jax.random.PRNGKey(0))
    toks = jnp.asarray(np.arange(8 * 128).reshape(8, 128) % 64, jnp.int32)

    np.testing.assert_allclose(np.asarray(m0.apply(params, toks)),
                               np.asarray(m1.apply(params, toks)),
                               rtol=1e-6, atol=1e-6)

    def loss(m):
        def f(p):
            return cross_entropy(m.apply(p, toks[:, :-1]), toks[:, 1:])
        return f

    g0 = jax.grad(loss(m0))(params)
    g1 = jax.grad(loss(m1))(params)
    for a, b in zip(jax.tree_util.tree_leaves(g0),
                    jax.tree_util.tree_leaves(g1)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)

    jaxpr0 = str(jax.make_jaxpr(jax.grad(loss(m0)))(params))
    jaxpr1 = str(jax.make_jaxpr(jax.grad(loss(m1)))(params))
    assert "remat" not in jaxpr0
    assert "remat" in jaxpr1

    mem0 = profiler.compiled_memory(jax.grad(loss(m0)), params)
    mem1 = profiler.compiled_memory(jax.grad(loss(m1)), params)
    if mem0.get("temp_size_bytes") and mem1.get("temp_size_bytes"):
        assert mem1["temp_size_bytes"] <= mem0["temp_size_bytes"]


class TestSyncBatchNorm:
    def test_sync_bn_matches_full_batch_stats(self, group8):
        """SyncBN inside an 8-way shard_map == local BN on the gathered
        global batch: same outputs, same (replica-identical) running
        stats."""
        from jax.sharding import PartitionSpec as P

        from distributed_pytorch_tpu.nn.conv import BatchNorm2d
        from distributed_pytorch_tpu.runtime import context

        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.standard_normal((16, 4, 4, 3)) * 3 + 1,
                        jnp.float32)
        bn_sync = BatchNorm2d(3, axis_name="dp")
        bn_local = BatchNorm2d(3)
        params = bn_sync.init(jax.random.PRNGKey(0))
        state = bn_sync.init_state()

        want_y, want_state = bn_local.apply(params, x, state=state,
                                            train=True)

        mesh = context.get_mesh()

        def island(x):
            y, ns = bn_sync.apply(params, x, state=state, train=True)
            return y, ns["mean"], ns["var"]

        y, nm, nv = jax.jit(shard_map(
            island, mesh=mesh,
            in_specs=P("dp"), out_specs=(P("dp"), P("dp"), P("dp")),
            check_vma=False))(x)
        # outputs equal the full-batch normalization
        np.testing.assert_allclose(np.asarray(y), np.asarray(want_y),
                                   rtol=2e-4, atol=2e-5)
        # every shard's running stats equal the full-batch update
        nm = np.asarray(nm).reshape(8, -1)
        nv = np.asarray(nv).reshape(8, -1)
        for r in range(8):
            np.testing.assert_allclose(nm[r], np.asarray(want_state["mean"]),
                                       rtol=2e-4, atol=2e-5)
            np.testing.assert_allclose(nv[r], np.asarray(want_state["var"]),
                                       rtol=2e-3, atol=2e-4)

    def test_sync_bn_degrades_outside_shard_map(self):
        """axis_name set but no axis bound (world-1 / plain jit): local
        statistics, no error — the 0/1/N contract."""
        from distributed_pytorch_tpu.nn.conv import BatchNorm2d

        rng = np.random.default_rng(1)
        x = jnp.asarray(rng.standard_normal((4, 2, 2, 3)), jnp.float32)
        bn_sync = BatchNorm2d(3, axis_name="dp")
        bn_local = BatchNorm2d(3)
        params = bn_sync.init(jax.random.PRNGKey(0))
        y_sync, _ = jax.jit(lambda x: bn_sync.apply(params, x,
                                                    train=True))(x)
        y_local, _ = bn_local.apply(params, x, train=True)
        np.testing.assert_allclose(np.asarray(y_sync),
                                   np.asarray(y_local),
                                   rtol=2e-5, atol=2e-6)

    @pytest.mark.slow
    def test_resnet_sync_bn_trains(self, group8):
        """ResNet18(sync_bn=True) trains under the stateful DP step."""
        from distributed_pytorch_tpu import optim
        from distributed_pytorch_tpu.ops.losses import cross_entropy
        from distributed_pytorch_tpu.parallel import (
            make_stateful_train_step, stack_state)
        import distributed_pytorch_tpu as dist

        model = models.ResNet18(n_classes=4, small_input=True,
                                sync_bn=True)
        params, state = model.init(jax.random.PRNGKey(0))
        opt = optim.adamw(1e-3)
        opt_state = opt.init(params)

        def loss_fn(p, st, batch):
            x, y = batch
            logits, ns = model.apply(p, x, state=st, train=True)
            return cross_entropy(logits, y), (ns, {})

        step = make_stateful_train_step(loss_fn, opt, donate=False)
        rng = np.random.default_rng(0)
        x = dist.shard_batch(
            rng.standard_normal((16, 8, 8, 3)).astype(np.float32))
        y = dist.shard_batch(rng.integers(0, 4, 16).astype(np.int32))
        params_r = dist.replicate(params)
        opt_r = dist.replicate(opt_state)
        state_s = stack_state(state)
        losses = []
        out = step(params_r, state_s, opt_r, (x, y))
        losses.append(float(jnp.mean(out.loss)))
        for _ in range(4):
            out = step(out.params, out.state, out.opt_state, (x, y))
            losses.append(float(jnp.mean(out.loss)))
        assert all(np.isfinite(losses))
        assert losses[-1] < losses[0]


class TestFusedLinearCrossEntropy:
    """fused_linear_cross_entropy streams the vocab projection chunkwise;
    it must match the materialize-then-CE path in value and gradients."""

    def _setup(self, n=37, d=16, v=53, seed=0):
        from distributed_pytorch_tpu.ops.losses import \
            fused_linear_cross_entropy
        k1, k2, k3 = jax.random.split(jax.random.PRNGKey(seed), 3)
        h = jax.random.normal(k1, (n, d), jnp.float32)
        w = jax.random.normal(k2, (d, v), jnp.float32) * 0.1
        y = jax.random.randint(k3, (n,), 0, v, jnp.int32)
        return fused_linear_cross_entropy, h, w, y

    def test_value_matches_unfused(self):
        fused, h, w, y = self._setup()
        ref = cross_entropy(h @ w, y)
        # chunk 8 does not divide 37 -> exercises the padding path
        got = fused(h, w, y, chunk_rows=8)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=1e-6)

    def test_single_chunk_and_batched_shapes(self):
        fused, h, w, y = self._setup(n=24)
        ref = cross_entropy(h @ w, y)
        np.testing.assert_allclose(
            np.asarray(fused(h, w, y, chunk_rows=1024)),
            np.asarray(ref), rtol=1e-6)
        # (B, S, d) hidden + (B, S) labels flatten internally
        np.testing.assert_allclose(
            np.asarray(fused(h.reshape(4, 6, -1), w, y.reshape(4, 6),
                             chunk_rows=7)),
            np.asarray(ref), rtol=1e-6)

    def test_grads_match_unfused(self):
        fused, h, w, y = self._setup()

        gh_ref, gw_ref = jax.grad(
            lambda h_, w_: cross_entropy(h_ @ w_, y), argnums=(0, 1))(h, w)
        gh, gw = jax.grad(
            lambda h_, w_: fused(h_, w_, y, chunk_rows=8),
            argnums=(0, 1))(h, w)
        np.testing.assert_allclose(np.asarray(gh), np.asarray(gh_ref),
                                   rtol=1e-5, atol=1e-7)
        np.testing.assert_allclose(np.asarray(gw), np.asarray(gw_ref),
                                   rtol=1e-5, atol=1e-7)

    def test_lm_training_with_fused_head(self):
        """End-to-end: TransformerLM return_hidden + fused CE trains, and
        the loss equals the standard logits path."""
        from distributed_pytorch_tpu.ops.losses import \
            fused_linear_cross_entropy
        model = models.TransformerLM(vocab=64, dim=32, n_layers=2, n_heads=4,
                                     max_seq=16)
        params = model.init(jax.random.PRNGKey(0))
        toks = jax.random.randint(jax.random.PRNGKey(1), (2, 17), 0, 64,
                                  jnp.int32)

        def loss_fused(p, t):
            hid = model.apply(p, t[:, :-1], return_hidden=True)
            return fused_linear_cross_entropy(hid, p["head"]["w"], t[:, 1:],
                                              chunk_rows=8), {}

        def loss_ref(p, t):
            return cross_entropy(model.apply(p, t[:, :-1]), t[:, 1:]), {}

        lf, _ = loss_fused(params, toks)
        lr, _ = loss_ref(params, toks)
        np.testing.assert_allclose(np.asarray(lf), np.asarray(lr), rtol=1e-6)

        opt = optim.adamw(1e-3)
        step = make_train_step(loss_fused, opt, donate=False)
        out = step(params, opt.init(params), toks)
        l0 = float(out.loss.mean())
        for _ in range(5):
            out = step(out.params, out.opt_state, toks)
        assert float(out.loss.mean()) < l0


class TestTiedEmbeddings:
    """tie_embeddings: the vocab projection reuses the token table
    transposed — no head parameter, logits = h @ emb.T."""

    def _model(self, **kw):
        return models.TransformerLM(vocab=61, dim=32, n_layers=2, n_heads=4,
                                    max_seq=32, tie_embeddings=True, **kw)

    def test_no_head_param_and_logits_use_emb(self):
        model = self._model()
        params = model.init(jax.random.PRNGKey(0))
        assert "head" not in params
        toks = jax.random.randint(jax.random.PRNGKey(1), (2, 8), 0, 61)
        hid = model.apply(params, toks, return_hidden=True)
        logits = model.apply(params, toks)
        want = np.asarray(hid) @ np.asarray(params["tok"]["emb"]).T
        np.testing.assert_allclose(np.asarray(logits), want, atol=1e-5)

    def test_trains_and_gradient_flows_through_both_uses(self):
        from distributed_pytorch_tpu.parallel import make_train_step
        model = self._model()
        params = model.init(jax.random.PRNGKey(0))
        toks = jax.random.randint(jax.random.PRNGKey(1), (2, 17), 0, 61)

        def loss_fn(p, t):
            return cross_entropy(model.apply(p, t[:, :-1]), t[:, 1:]), {}

        opt = optim.adamw(1e-3)
        step = make_train_step(loss_fn, opt, donate=False)
        out = step(params, opt.init(params), toks)
        l0 = float(out.loss.mean())
        for _ in range(5):
            out = step(out.params, out.opt_state, toks)
        assert float(out.loss.mean()) < l0

    @pytest.mark.slow
    def test_cached_decode_matches_full_forward(self):
        from distributed_pytorch_tpu.models.generate import make_generate_fn
        model = self._model(n_kv_heads=2, pos="rope")
        params = model.init(jax.random.PRNGKey(0))
        prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 5), 0, 61)
        out = np.asarray(make_generate_fn(model, 5)(
            params, prompt, jax.random.PRNGKey(2)))
        toks = np.asarray(prompt)
        want = []
        for _ in range(5):
            logits = model.apply(params, jnp.asarray(toks))
            nxt = np.asarray(jnp.argmax(logits[:, -1], axis=-1))
            want.append(nxt)
            toks = np.concatenate([toks, nxt[:, None]], axis=1)
        np.testing.assert_array_equal(out, np.stack(want, axis=1))

    def test_fused_ce_uses_head_weight(self):
        from distributed_pytorch_tpu.ops.losses import \
            fused_linear_cross_entropy
        model = self._model()
        params = model.init(jax.random.PRNGKey(0))
        toks = jax.random.randint(jax.random.PRNGKey(3), (2, 9), 0, 61)
        hid = model.apply(params, toks[:, :-1], return_hidden=True)
        fused = fused_linear_cross_entropy(hid, model.head_weight(params),
                                           toks[:, 1:], chunk_rows=8)
        ref = cross_entropy(model.apply(params, toks[:, :-1]), toks[:, 1:])
        np.testing.assert_allclose(np.asarray(fused), np.asarray(ref),
                                   rtol=1e-6)

    def test_param_count_saving(self):
        tied = self._model().init(jax.random.PRNGKey(0))
        untied = models.TransformerLM(vocab=61, dim=32, n_layers=2,
                                      n_heads=4, max_seq=32).init(
                                          jax.random.PRNGKey(0))
        n = lambda p: sum(int(np.prod(l.shape))
                          for l in jax.tree_util.tree_leaves(p))
        assert n(untied) - n(tied) == 61 * 32


class TestVocabParallelCE:
    def test_matches_gathered_loss_and_grads(self):
        """Megatron-style vocab-parallel CE: the tp island (local
        projection slice + scalar-per-token collectives) equals the
        gathered softmax-CE in value AND gradients — the (B,S,V) logits
        never exist on any device."""
        from distributed_pytorch_tpu.ops import make_vocab_parallel_ce_fn
        from distributed_pytorch_tpu.runtime import context

        mesh = context.init_mesh(dp=2, tp=4)
        try:
            rng = np.random.default_rng(0)
            B, S, D, V = 4, 6, 16, 32
            h = jnp.asarray(rng.standard_normal((B, S, D)), jnp.float32)
            w = jnp.asarray(rng.standard_normal((D, V)) * 0.2,
                            jnp.float32)
            y = jnp.asarray(rng.integers(0, V, (B, S)).astype(np.int32))
            fn = make_vocab_parallel_ce_fn(mesh)

            got = jax.jit(fn)(h, w, y)
            want = cross_entropy_per_example(jnp.matmul(h, w), y)
            np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                       rtol=2e-5, atol=2e-5)

            gv = jax.jit(jax.grad(
                lambda h, w: jnp.mean(fn(h, w, y)),
                argnums=(0, 1)))(h, w)
            gd = jax.grad(
                lambda h, w: jnp.mean(cross_entropy_per_example(
                    jnp.matmul(h, w), y)), argnums=(0, 1))(h, w)
            for a, b in zip(gv, gd):
                np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                           rtol=5e-5, atol=5e-5)
        finally:
            dist.cleanup()

    def test_unowned_labels_surface_as_nan(self):
        """A label no tp shard owns (ignore-index padding like -100)
        must surface as NaN like the gathered path — not silent finite
        garbage that corrupts training."""
        from distributed_pytorch_tpu.ops import make_vocab_parallel_ce_fn
        from distributed_pytorch_tpu.runtime import context

        mesh = context.init_mesh(dp=2, tp=4)
        try:
            rng = np.random.default_rng(1)
            h = jnp.asarray(rng.standard_normal((2, 4, 8)), jnp.float32)
            w = jnp.asarray(rng.standard_normal((8, 16)) * 0.3,
                            jnp.float32)
            y = jnp.asarray(rng.integers(0, 16, (2, 4)).astype(np.int32))
            y = y.at[0, 0].set(-100).at[1, 3].set(16)
            out = np.asarray(jax.jit(make_vocab_parallel_ce_fn(mesh))(
                h, w, y))
            assert np.isnan(out[0, 0]) and np.isnan(out[1, 3])
            mask = np.ones_like(out, bool)
            mask[0, 0] = mask[1, 3] = False
            assert np.isfinite(out[mask]).all()
        finally:
            dist.cleanup()
