"""dpxtrace observability (obs/) — acceptance + units (ISSUE 14).

The headline contracts: (1) a world-4 chaos run (kill@op=allreduce)
produces a MERGED Chrome trace that parses, with spans from EVERY rank,
and the injected failure's flight-recorder dump names the dying op on
every survivor; (2) a disaggregated serve request shows ONE trace_id
spanning prefill→handoff→decode, with span durations summing exactly to
the TTFT decomposition ``serve/metrics.py`` asserts; (3) the flight
recorder ring wraps with drop ACCOUNTING (never silent loss); (4)
``utils.logging`` event timestamps are monotone non-decreasing even
when the system clock steps backwards (the perf_counter_ns + wall
anchor satellite).
"""

import json
import multiprocessing as mp
import threading
import time

import numpy as np
import pytest

from distributed_pytorch_tpu.obs import detect, export, trace
from distributed_pytorch_tpu.runtime import faults
from distributed_pytorch_tpu.runtime.multiprocess import launch_multiprocess
from distributed_pytorch_tpu.runtime.watchdog import WorkerFailure
from distributed_pytorch_tpu.serve.metrics import aggregate, percentile
from distributed_pytorch_tpu.utils import logging as dpxlog

TIMEOUT_MS = 2000  # per-op deadline for the chaos run


@pytest.fixture(autouse=True)
def _clean_trace():
    """Every test starts and ends with pristine tracing state (the
    module is process-global) and no leftover fault specs."""
    trace.reset()
    faults.reset()
    yield
    trace.reset()
    faults.reset()


def _enable(tmp_path, ring=256):
    log = tmp_path / "trace.jsonl"
    trace.configure(enabled=True, ring=ring, log_path=str(log))
    return log


# ---------------------------------------------------------------------------
# span core
# ---------------------------------------------------------------------------


class TestSpanCore:
    def test_disabled_span_records_nothing(self, tmp_path):
        log = tmp_path / "t.jsonl"
        trace.configure(enabled=False, log_path=str(log))
        with trace.span("x", a=1):
            pass
        spans, dropped = trace.flight_snapshot()
        assert spans == [] and dropped == 0
        assert not log.exists()

    def test_span_nesting_and_lineage(self, tmp_path):
        log = _enable(tmp_path)
        with trace.span("outer", trace_id="T1") as outer:
            with trace.span("inner") as inner:
                pass
        recs, bad = export.read_log(str(log))
        assert bad == []
        by_name = {r["name"]: r for r in recs}
        assert by_name["inner"]["parent_id"] == outer.span_id
        # ambient trace id flows to children
        assert by_name["inner"]["trace_id"] == "T1"
        assert by_name["inner"]["dur_ns"] >= 0
        assert by_name["outer"]["parent_id"] is None
        # inner closed before outer
        assert inner.t1_ns <= outer.t1_ns

    def test_span_exception_annotated_and_stack_repaired(self, tmp_path):
        log = _enable(tmp_path)
        with pytest.raises(ValueError):
            with trace.span("boom"):
                raise ValueError("x")
        # the ambient stack is clean again — a fresh span is a root
        with trace.span("after"):
            pass
        recs, _ = export.read_log(str(log))
        by_name = {r["name"]: r for r in recs}
        assert by_name["boom"]["attrs"]["error"] == "ValueError"
        assert by_name["after"]["parent_id"] is None

    def test_instant_event_attaches_to_open_span(self, tmp_path):
        log = _enable(tmp_path)
        with trace.span("op"):
            trace.event("fault_injected", action="delay")
        recs, _ = export.read_log(str(log))
        (rec,) = [r for r in recs if r["name"] == "op"]
        assert rec["events"][0]["name"] == "fault_injected"
        assert rec["events"][0]["action"] == "delay"

    def test_wall_now_monotone_and_anchored(self):
        stamps = [trace.wall_now() for _ in range(200)]
        assert stamps == sorted(stamps)
        # anchored to real wall time (within a generous minute)
        assert abs(stamps[-1] - time.time()) < 60.0

    def test_wall_from_mono_consistent_with_wall_now(self):
        m = time.monotonic()
        w = trace.wall_from_mono(m)
        assert abs(w - trace.wall_now()) < 0.1


# ---------------------------------------------------------------------------
# flight recorder: wraparound + drop accounting + dump idempotence
# ---------------------------------------------------------------------------


class TestFlightRecorder:
    def test_ring_wraparound_counts_drops(self, tmp_path):
        _enable(tmp_path, ring=4)
        for i in range(10):
            with trace.span(f"s{i}"):
                pass
        spans, dropped = trace.flight_snapshot()
        assert [s["name"] for s in spans] == ["s6", "s7", "s8", "s9"]
        assert dropped == 6  # 10 recorded, 4 resident — NEVER silent

    def test_flight_dump_ships_last_n_and_is_idempotent(self, tmp_path):
        log = _enable(tmp_path, ring=4)
        for i in range(6):
            with trace.span(f"s{i}"):
                pass
        assert trace.flight_dump("CommPeerDied", op="allreduce")
        # no new spans since → a teardown cascade dumps exactly once
        assert not trace.flight_dump("CommPeerDied", op="allreduce")
        recs, _ = export.read_log(str(log))
        dumps = [r for r in recs if r["event"] == "flight_recorder"]
        assert len(dumps) == 1
        d = dumps[0]
        assert d["reason"] == "CommPeerDied" and d["op"] == "allreduce"
        assert d["n_spans"] == 4 and d["dropped"] == 2
        assert [s["name"] for s in d["spans"]] == ["s2", "s3", "s4",
                                                   "s5"]

    def test_empty_ring_dumps_nothing(self, tmp_path):
        log = _enable(tmp_path)
        assert not trace.flight_dump("WorkerFailure")
        assert not (log.exists() and "flight_recorder" in log.read_text())

    def test_on_typed_failure_lifts_attribution(self, tmp_path):
        from distributed_pytorch_tpu.runtime.native import CommTimeout
        log = _enable(tmp_path)
        with trace.span("comm:allreduce"):
            pass
        exc = CommTimeout("deadline", op="allreduce", rank=2, peer=1,
                          deadline_ms=500)
        assert trace.on_typed_failure(exc)
        recs, _ = export.read_log(str(log))
        (d,) = [r for r in recs if r["event"] == "flight_recorder"]
        assert d["reason"] == "CommTimeout"
        assert d["err_op"] == "allreduce" and d["err_peer"] == 1
        assert d["rank"] == 2  # falls back to the error's rank


# ---------------------------------------------------------------------------
# monotone logging timestamps (the utils/logging satellite)
# ---------------------------------------------------------------------------


class TestMonotoneLogging:
    def test_append_event_survives_clock_step_backwards(
            self, tmp_path, monkeypatch):
        log = tmp_path / "m.jsonl"
        monkeypatch.setenv("DPX_METRICS_LOG", str(log))
        dpxlog.append_event("ckpt_save", step=1)
        # the system clock steps BACK two hours mid-run (NTP) — event
        # order in the log must still be non-decreasing
        walk = iter([time.time() - 7200.0] * 10)
        monkeypatch.setattr(time, "time", lambda: next(walk))
        dpxlog.append_event("ckpt_save", step=2)
        dpxlog.append_event("ckpt_save", step=3)
        recs, bad = export.read_log(str(log))
        assert bad == []
        times = [r["time"] for r in recs]
        assert times == sorted(times)
        assert all(t > 1e9 for t in times)  # still real wall stamps

    def test_metrics_logger_monotone(self, tmp_path, monkeypatch):
        log = tmp_path / "m2.jsonl"
        ml = dpxlog.MetricsLogger(str(log))
        ml.log(step=1, loss=1.0)
        monkeypatch.setattr(time, "time",
                            lambda: 12.0)  # absurd backwards clock
        ml.log(step=2, loss=0.9)
        ml.event("worker_failure", rank=0)
        ml.close()
        recs, _ = export.read_log(str(log))
        times = [r["time"] for r in recs]
        assert times == sorted(times) and all(t > 1e9 for t in times)


# ---------------------------------------------------------------------------
# export: merge, rank→pid, clock alignment, validator
# ---------------------------------------------------------------------------


def _mk_span(name, rank, t0, dur_s, span_id, **attrs):
    rec = {"event": "trace_span", "name": name, "trace_id": None,
           "span_id": span_id, "parent_id": None, "t0_wall": t0,
           "dur_ns": int(dur_s * 1e9), "rank": rank, "pid": 1000 + rank,
           "tid": "MainThread"}
    if attrs:
        rec["attrs"] = attrs
    return rec


class TestExport:
    def test_chrome_trace_rank_to_pid_and_parses(self):
        recs = [_mk_span("comm:allreduce", r, 100.0 + r * 0.001, 0.01,
                         f"{r}.1") for r in range(4)]
        ct = export.chrome_trace(recs)
        text = json.dumps(ct)          # must be valid JSON end to end
        parsed = json.loads(text)
        xs = [e for e in parsed["traceEvents"] if e["ph"] == "X"]
        assert {e["pid"] for e in xs} == {0, 1, 2, 3}
        names = [e for e in parsed["traceEvents"] if e["ph"] == "M"]
        assert {m["args"]["name"] for m in names} == {
            "rank 0", "rank 1", "rank 2", "rank 3"}

    def test_clock_alignment_from_matched_collective_exits(self):
        # rank 1's anchor is skewed +5s; its barrier EXITS line up with
        # rank 0's after the estimated offset is subtracted
        recs = []
        for k in range(3):
            base = 100.0 + k
            recs.append(_mk_span("comm:barrier", 0, base, 0.010,
                                 f"0.b{k}"))
            recs.append(_mk_span("comm:barrier", 1, base + 5.0, 0.010,
                                 f"1.b{k}"))
        spans = export.collect_spans(recs)
        offsets = export.estimate_offsets(spans)
        assert abs(offsets[1] - 5.0) < 1e-6 and offsets[0] == 0.0
        ct = export.chrome_trace(recs)
        ts = {(e["pid"], e["name"], round(e["ts"])): e["ts"]
              for e in ct["traceEvents"] if e["ph"] == "X"}
        # after alignment the k-th barrier starts at the same µs on
        # both rank rows
        for k in range(3):
            t0 = (100.0 + k) * 1e6
            assert abs(ts[(0, "comm:barrier", round(t0))] - t0) < 1
            assert abs(ts[(1, "comm:barrier", round(t0))] - t0) < 1

    def test_flight_recorder_spans_dedupe_into_trace(self, tmp_path):
        log = _enable(tmp_path, ring=8)
        trace.set_rank(3)
        with trace.span("comm:allreduce"):
            pass
        trace.flight_dump("CommPeerDied", op="allreduce")
        recs, _ = export.read_log(str(log))
        spans = export.collect_spans(recs)
        # the live-logged span and its flight-recorder copy are ONE
        assert len(spans) == 1 and spans[0]["rank"] == 3

    def test_check_flags_the_three_issue_classes(self, tmp_path):
        log = tmp_path / "bad.jsonl"
        lines = [
            json.dumps({"event": "worker_failure", "rank": 1,
                        "time": 1.0}),
            "{not json",
            json.dumps({"event": "totally_unknown", "time": 1.0}),
            json.dumps({"event": "worker_failure", "time": 2.0}),
            json.dumps({"step": 3, "time": 3.0, "loss": 0.5}),
            json.dumps({"neither": True}),
        ]
        log.write_text("\n".join(lines) + "\n")
        issues = export.check_log(*export.read_log(str(log)))
        msgs = "\n".join(m for _, m in issues)
        lines_flagged = {ln for ln, _ in issues}
        assert any("malformed" in m for _, m in issues)
        assert 2 in lines_flagged          # the broken line, BY NUMBER
        assert "unknown event name 'totally_unknown'" in msgs
        assert "no rank attribution" in msgs
        assert "neither a named event nor a step record" in msgs
        # the well-formed failure event and the step record pass
        assert 1 not in lines_flagged and 5 not in lines_flagged

    def test_dpxtrace_cli_check_and_export(self, tmp_path, capsys):
        from tools import dpxtrace as cli
        log = _enable(tmp_path)
        with trace.span("comm:allreduce", bytes=64):
            pass
        assert cli.main(["check", str(log)]) == 0
        out = tmp_path / "chrome.json"
        assert cli.main(["export", str(log), "-o", str(out)]) == 0
        parsed = json.loads(out.read_text())
        assert parsed["otherData"]["n_spans"] == 1
        (log.parent / "broken.jsonl").write_text("{nope\n")
        assert cli.main(["--check",
                         str(log.parent / "broken.jsonl")]) == 1


# ---------------------------------------------------------------------------
# straggler detection
# ---------------------------------------------------------------------------


class TestDetect:
    def _spans(self, medians_by_rank, n=8):
        recs = []
        for rank, med in medians_by_rank.items():
            for i in range(n):
                recs.append(_mk_span("comm:allreduce", rank, 100.0 + i,
                                     med * (1 + 0.01 * (i % 3)),
                                     f"{rank}.{i}"))
        return export.collect_spans(recs)

    def test_straggler_rank_flagged(self):
        # ranks 0-2 at ~10ms, rank 3 at ~40ms — the classic one-slow-
        # rank pathology (arXiv 1810.11112)
        found = detect.stragglers(self._spans(
            {0: 0.010, 1: 0.0101, 2: 0.0099, 3: 0.040}))
        assert len(found) == 1
        f = found[0]
        assert f["rank"] == 3 and f["op"] == "comm:allreduce"
        assert f["excess_x"] > 3.0

    def test_uniform_ranks_not_flagged(self):
        found = detect.stragglers(self._spans(
            {0: 0.010, 1: 0.0101, 2: 0.0099, 3: 0.0102}))
        assert found == []

    def test_single_rank_op_skipped(self):
        assert detect.stragglers(self._spans({0: 0.010})) == []

    def test_summarize_ops_rows(self):
        rows = detect.summarize_ops(self._spans({0: 0.01, 1: 0.02}))
        assert {r["rank"] for r in rows} == {0, 1}
        assert all(r["op"] == "comm:allreduce" and r["count"] == 8
                   for r in rows)


# ---------------------------------------------------------------------------
# serve/metrics aggregate() edge cases (satellite)
# ---------------------------------------------------------------------------


class TestAggregateEdges:
    def test_empty_window(self):
        out = aggregate([])
        assert out["n_requests"] == 0 and out["n_ok"] == 0
        assert out["ttft_ms_p50"] is None
        assert out["tpot_ms_p99"] is None
        assert out["outcomes"] == {}
        assert out["total_tokens"] == 0

    def test_single_sample(self):
        rec = {"outcome": "ok", "ttft_ms": 12.0, "tpot_ms": None,
               "n_tokens": 1, "prompt_len": 4, "queue_ms": 1.0}
        out = aggregate([rec], wall_s=2.0)
        assert out["ttft_ms_p50"] == 12.0 and out["ttft_ms_p99"] == 12.0
        assert out["tpot_ms_p50"] is None  # 1-token stream: undefined
        assert out["tokens_per_sec"] == 0.5

    def test_all_failed_requests(self):
        recs = [{"outcome": "deadline_queued", "ttft_ms": None,
                 "tpot_ms": None, "n_tokens": 0, "prompt_len": 4},
                {"outcome": "engine_stopped", "ttft_ms": None,
                 "tpot_ms": None, "n_tokens": 0, "prompt_len": 4}]
        out = aggregate(recs)
        assert out["n_requests"] == 2 and out["n_ok"] == 0
        assert out["outcomes"] == {"deadline_queued": 1,
                                   "engine_stopped": 1}
        assert out["ttft_ms_p50"] is None and out["total_tokens"] == 0

    def test_percentile_empty_and_none_filtered(self):
        assert percentile([], 50) is None
        assert percentile([None, None], 99) is None
        assert percentile([None, 3.0], 50) == 3.0


# ---------------------------------------------------------------------------
# serve lifecycle: ONE trace_id, spans == the TTFT decomposition
# ---------------------------------------------------------------------------


def _lm(**kw):
    from distributed_pytorch_tpu import models
    kw.setdefault("vocab", 61)
    kw.setdefault("dim", 32)
    kw.setdefault("n_layers", 1)
    kw.setdefault("n_heads", 4)
    kw.setdefault("n_kv_heads", 2)
    kw.setdefault("pos", "rope")
    kw.setdefault("max_seq", 128)
    return models.TransformerLM(**kw)


class TestServeTrace:
    def test_monolithic_request_spans_one_trace_id(self, tmp_path):
        import jax
        from distributed_pytorch_tpu.serve import (EngineConfig,
                                                   InferenceEngine,
                                                   SamplingParams)
        log = _enable(tmp_path)
        model = _lm()
        params = model.init(jax.random.PRNGKey(0))
        prompt = np.arange(5, dtype=np.int32) % 61
        with InferenceEngine(model, params,
                             EngineConfig(n_slots=2, max_len=64)) as eng:
            h = eng.submit(prompt, SamplingParams(max_new_tokens=4))
            h.result(timeout=120)
        recs, _ = export.read_log(str(log))
        spans = [r for r in recs if r.get("event") == "trace_span"
                 and str(r["name"]).startswith("serve.")]
        by_name = {s["name"]: s for s in spans}
        assert {"serve.request", "serve.queue", "serve.prefill",
                "serve.stream"} <= set(by_name)
        tids = {s["trace_id"] for s in spans}
        assert len(tids) == 1 and tids == {h.metrics["trace_id"]}
        root = by_name["serve.request"]
        assert all(s["parent_id"] == root["span_id"]
                   for s in spans if s is not root)
        # queue + prefill telescope to TTFT (same timestamps, exactly)
        # abs tolerance 0.02 ms: the spans' wall stamps carry the
        # anchor's float ulp (~0.5 µs per value at 1.7e9 s magnitude)
        ttft = (by_name["serve.queue"]["dur_ns"]
                + by_name["serve.prefill"]["dur_ns"]) / 1e6
        assert ttft == pytest.approx(h.metrics["ttft_ms"], abs=0.02)

    def test_disagg_one_trace_id_spans_sum_to_ttft(self, tmp_path):
        import jax
        from distributed_pytorch_tpu.serve import (DisaggConfig,
                                                   DisaggEngine,
                                                   SamplingParams)
        log = _enable(tmp_path)
        model = _lm()
        params = model.init(jax.random.PRNGKey(0))
        prompt = (np.arange(9, dtype=np.int32) * 3) % 61
        with DisaggEngine(model, params,
                          DisaggConfig(n_slots=2, max_len=64,
                                       page_len=8)) as eng:
            h = eng.submit(prompt, SamplingParams(max_new_tokens=4))
            h.result(timeout=120)
        rec = h.metrics
        recs, _ = export.read_log(str(log))
        spans = [r for r in recs if r.get("event") == "trace_span"
                 and str(r["name"]).startswith("serve.")]
        by_name = {s["name"]: s for s in spans}
        # the acceptance shape: ONE trace id across the whole split
        assert {"serve.request", "serve.queue", "serve.prefill",
                "serve.handoff", "serve.decode"} <= set(by_name)
        assert len({s["trace_id"] for s in spans}) == 1
        assert {s["trace_id"] for s in spans} == {rec["trace_id"]}
        # span durations sum EXACTLY to the asserted TTFT decomposition
        # (queue→prefill→handoff→decode telescopes to first_token −
        # submit; serve/metrics.py asserts the same identity in ms)
        total_ms = sum(by_name[n]["dur_ns"] for n in
                       ("serve.queue", "serve.prefill", "serve.handoff",
                        "serve.decode")) / 1e6
        # abs 0.02 ms = 4 spans × the wall anchor's float ulp (~0.5 µs
        # per stamp at 1.7e9 s magnitude) — far below any real leg
        assert total_ms == pytest.approx(rec["ttft_ms"], abs=0.02)
        parts = sum(rec[k] for k in ("queue_ms", "prefill_ms",
                                     "handoff_ms", "decode_ms"))
        assert total_ms == pytest.approx(parts, abs=0.02)


# ---------------------------------------------------------------------------
# THE chaos acceptance: world 4, kill@op=allreduce, tracing on
# ---------------------------------------------------------------------------


def _obs_chaos_worker(rank, world, q):
    """Two clean allreduces + a barrier (an alignment point for the
    export), then rank 1 is killed entering allreduce call 3."""
    import numpy as np

    import distributed_pytorch_tpu as dist

    dist.init_process_group(rank, world)
    dist.barrier()
    for _ in range(2):
        dist.all_reduce(np.ones(4096, np.float32))
    try:
        dist.all_reduce(np.ones(4096, np.float32))
        q.put((rank, None))
    except Exception as e:  # noqa: BLE001 — typed comm error expected
        q.put((rank, type(e).__name__))
        raise


def test_chaos_world4_merged_trace_and_flight_dumps(tmp_path,
                                                    monkeypatch):
    """Acceptance (ISSUE 14): a world-4 chaos run with tracing on and a
    DPX_FAULT kill mid-allreduce yields (1) a merged Chrome trace that
    PARSES and contains spans from every rank, (2) flight-recorder
    dumps from the survivors naming the dying op, and (3) a clock-
    offset estimate for every rank present."""
    log = tmp_path / "chaos.jsonl"
    monkeypatch.setenv("DPX_TRACE", "1")
    monkeypatch.setenv("DPX_METRICS_LOG", str(log))
    monkeypatch.setenv(faults.FAULT_ENV, "kill@op=allreduce,call=3,rank=1")
    monkeypatch.setenv("DPX_COMM_TIMEOUT_MS", str(TIMEOUT_MS))
    ctx = mp.get_context("spawn")
    q = ctx.Queue()
    result = {}

    def run():
        try:
            launch_multiprocess(_obs_chaos_worker, 4, q)
        except BaseException as e:  # noqa: BLE001
            result["exc"] = e

    t = threading.Thread(target=run, name="test-obs-chaos", daemon=True)
    t.start()
    t.join(timeout=120)
    assert not t.is_alive(), "chaos run hung"
    assert isinstance(result.get("exc"), WorkerFailure)
    assert result["exc"].rank == 1 and result["exc"].op == "allreduce"

    records, malformed = export.read_log(str(log))
    assert malformed == []
    # (1) the merged Chrome trace parses and carries per-rank timelines
    ct = export.chrome_trace(records)
    parsed = json.loads(json.dumps(ct))
    span_pids = {e["pid"] for e in parsed["traceEvents"]
                 if e["ph"] == "X"}
    assert {0, 1, 2, 3} <= span_pids, \
        f"spans missing for ranks: { {0, 1, 2, 3} - span_pids }"
    # the killed rank's timeline includes its completed collectives
    rank1 = [e for e in parsed["traceEvents"]
             if e["ph"] == "X" and e["pid"] == 1]
    # CommStats books the exact ring as allreduce_sum — the victim's
    # two clean collectives are on its timeline
    assert any(e["name"].startswith("comm:allreduce") for e in rank1)
    # (3) every rank got a clock-offset estimate (barrier alignment)
    assert set(ct["otherData"]["clock_offsets_s"]) == {"0", "1", "2",
                                                       "3"}
    # (2) flight-recorder dumps: every SURVIVOR ships a postmortem that
    # names the dying op; the victim ships its own via the kill hook
    dumps = [r for r in records if r.get("event") == "flight_recorder"]
    by_rank = {}
    for d in dumps:
        by_rank.setdefault(d.get("rank"), []).append(d)
    assert {0, 2, 3} <= set(by_rank), \
        f"survivor dumps missing: {sorted(by_rank)}"
    for r in (0, 2, 3):
        d = by_rank[r][0]
        assert d["err_op"] == "allreduce", d
        assert d["reason"] in ("CommPeerDied", "CommTimeout")
        assert d["n_spans"] >= 1
    assert 1 in by_rank and by_rank[1][0]["reason"] == "fault_kill"
    # the stream itself passes the strict validator
    assert export.check_log(records, malformed) == []


def test_fault_delay_annotated_on_timeline(tmp_path, monkeypatch):
    """An injected delay shows up as a fault_injected instant event on
    the rank's timeline (inside the comm span when one is open)."""
    log = _enable(tmp_path)
    faults.install("delay@op=allreduce,ms=5")
    faults.on_comm_op("allreduce", rank=0)
    recs, _ = export.read_log(str(log))
    # no span open at the hook point → a standalone instant record
    insts = [r for r in recs if r.get("ph") == "i"
             and r["name"] == "fault_injected"]
    assert len(insts) == 1
    assert insts[0]["attrs"]["action"] == "delay"
