"""dpxmon live monitoring (obs/metrics.py + obs/health.py +
tools/dpxmon.py) — acceptance + units (ISSUE 15).

The headline contracts: (1) the registry's instruments snapshot into
rank-attributed ``metrics_snapshot`` events that pass BOTH strict
validators (dpxmon's snapshot shape, dpxtrace's event vocabulary);
(2) the streaming health evaluator walks ok → degraded → critical with
hysteresis and emits transitions that name the firing rule and metric;
(3) the health-rule edge cases: hysteresis across the ok↔degraded
boundary, single-snapshot windows, all-ranks-missing snapshots, and
the ``obs/detect.py`` small-sample IQR degeneracy (n<=2); (4) the
``tools/dpxmon.py`` CLI replays clean logs to exit 0 and seeded
SLO-violation logs to exit 1.
"""

import json
import time

import pytest

from distributed_pytorch_tpu.obs import detect, export, health, metrics
from distributed_pytorch_tpu.obs import trace


@pytest.fixture(autouse=True)
def _clean_registry():
    """Every test starts and ends with a pristine process-global
    registry (and tracing state — the snapshot built-ins read it)."""
    metrics.reset()
    trace.reset()
    yield
    metrics.reset()
    trace.reset()


def _snap(rank=0, t=100.0, step=1, source="test", **m):
    return {"event": "metrics_snapshot", "time": t, "rank": rank,
            "step": step, "source": source, "metrics": m}


def _hist(p99, count=8):
    return {"count": count, "sum": p99 * count, "min": p99, "max": p99,
            "p50": p99, "p99": p99}


# ---------------------------------------------------------------------------
# registry instruments
# ---------------------------------------------------------------------------


class TestRegistry:
    def test_counter_gauge_histogram_snapshot(self):
        metrics.configure(enabled=True, rank=3)
        metrics.inc("a.count", 2)
        metrics.inc("a.count")
        metrics.set_gauge("a.gauge", 1.5)
        for v in range(10):
            metrics.observe("a.hist", float(v))
        snap = metrics.snapshot()
        assert snap["a.count"] == 3
        assert snap["a.gauge"] == 1.5
        h = snap["a.hist"]
        assert h["count"] == 10 and h["min"] == 0.0 and h["max"] == 9.0
        assert h["p50"] == 5.0 and h["p99"] == 9.0

    def test_disabled_instruments_record_nothing(self):
        metrics.configure(enabled=False)
        metrics.inc("x")
        metrics.set_gauge("y", 1.0)
        metrics.observe("z", 1.0)
        metrics.configure(enabled=True)
        snap = metrics.snapshot()
        assert "x" not in snap and "y" not in snap and "z" not in snap

    def test_histogram_reservoir_bounded_cumulative_totals(self):
        metrics.configure(enabled=True)
        h = metrics.histogram("b.hist")
        for v in range(1000):
            h.observe(float(v))
        assert len(h.recent) == metrics.RESERVOIR_CAP
        s = h.snap()
        # cumulative count/min/max never drop; percentiles are over
        # the bounded RECENT window
        assert s["count"] == 1000 and s["min"] == 0.0
        assert s["p50"] >= 1000 - metrics.RESERVOIR_CAP

    def test_type_collision_raises(self):
        metrics.configure(enabled=True)
        metrics.inc("name")
        with pytest.raises(TypeError):
            metrics.gauge("name")

    def test_provider_polled_at_snapshot_and_never_fatal(self):
        metrics.configure(enabled=True)
        metrics.register_provider("good", lambda: {"p.val": 7})

        def boom():
            raise RuntimeError("provider crashed")

        metrics.register_provider("bad", boom)
        snap = metrics.snapshot()
        assert snap["p.val"] == 7          # good provider polled
        assert "proc.rss_bytes" in snap    # built-in RSS

    def test_emit_snapshot_rank_attributed_and_validates(self, tmp_path):
        log = tmp_path / "m.jsonl"
        metrics.configure(enabled=True, rank=2)
        metrics.inc("train.steps", 4)
        assert metrics.emit_snapshot(path=str(log), step=4,
                                     source="unit")
        recs, bad = export.read_log(str(log))
        assert bad == []
        (rec,) = recs
        assert rec["event"] == "metrics_snapshot" and rec["rank"] == 2
        assert metrics.validate_snapshot(rec) == []
        # the event name is in the dpxtrace vocabulary: the strict
        # log validator accepts the stream (the DPX008 contract)
        assert export.check_log(recs, bad) == []

    def test_on_train_step_cadence_and_steps_per_sec(self, tmp_path,
                                                     monkeypatch):
        log = tmp_path / "cadence.jsonl"
        monkeypatch.setenv("DPX_METRICS_LOG", str(log))
        metrics.configure(enabled=True, every=2, rank=0)
        for _ in range(6):
            metrics.on_train_step("unit")
            time.sleep(0.002)
        recs, _ = export.read_log(str(log))
        snaps = [r for r in recs if r["event"] == "metrics_snapshot"]
        assert [r["step"] for r in snaps] == [2, 4, 6]
        last = snaps[-1]["metrics"]
        assert last["train.steps"] == 6
        assert last["train.step_ms"]["count"] == 5   # gaps, not calls
        assert last["train.steps_per_sec"] > 0

    def test_validate_snapshot_flags_each_issue_class(self):
        good = _snap(v=1.0, h=_hist(5.0))
        assert metrics.validate_snapshot(good) == []
        no_rank = _snap(v=1.0)
        no_rank.pop("rank")
        assert any("rank" in m
                   for m in metrics.validate_snapshot(no_rank))
        bad_val = _snap(v="a string")
        assert any("neither a number" in m
                   for m in metrics.validate_snapshot(bad_val))
        bad_hist = _snap(h={"count": 1})
        assert any("histogram summary" in m
                   for m in metrics.validate_snapshot(bad_hist))
        no_metrics = {"event": "metrics_snapshot", "time": 1.0,
                      "rank": 0, "source": "t"}
        assert any("metrics dict" in m
                   for m in metrics.validate_snapshot(no_metrics))


# ---------------------------------------------------------------------------
# health rules + state machine
# ---------------------------------------------------------------------------


class TestRuleGrammar:
    def test_parse_all_kinds(self):
        rules = health.parse_rules(
            "serve.ttft_ms.p99<=500;train.steps_per_sec>=2;"
            "drift(train.steps_per_sec)@k=2.5,floor=0.2,name=slow;"
            "growth(proc.rss_bytes)@window=6,grow=0.03")
        kinds = {r.name: r for r in rules}
        assert kinds["serve.ttft_ms.p99<=500"].kind == "max"
        assert kinds["train.steps_per_sec>=2"].kind == "min"
        assert kinds["slow"].kind == "drift"
        assert kinds["slow"].k == 2.5 and kinds["slow"].rel_floor == 0.2
        g = kinds["growth:proc.rss_bytes"]
        assert g.window == 6 and g.min_growth == 0.03

    def test_malformed_specs_raise(self):
        for bad in ("nonsense", "a<=notanum", "drift()",
                    "a<=1@window"):
            with pytest.raises(ValueError):
                health.parse_rules(bad)

    def test_unevaluable_window_raises(self):
        """drift needs >= 3 trailing values and growth >= 4 history
        entries, both trimmed to the window — window < 4 could NEVER
        evaluate, i.e. the silently-vacuous SLO the parser's contract
        rejects."""
        for bad in ("drift(x)@window=3", "growth(x)@window=2"):
            with pytest.raises(ValueError):
                health.parse_rules(bad)
        assert health.parse_rules("growth(x)@window=4")[0].window == 4

    def test_resolve_metric_hist_suffix_and_absent(self):
        m = {"a": 1.0, "h": _hist(9.0)}
        assert health.resolve_metric(m, "a") == 1.0
        assert health.resolve_metric(m, "h.p99") == 9.0
        assert health.resolve_metric(m, "h") is None     # needs suffix
        assert health.resolve_metric(m, "missing") is None
        assert health.resolve_metric(m, "h.p12345") is None


class TestStateMachine:
    def _mon(self, spec, **kw):
        return health.HealthMonitor(health.parse_rules(spec), **kw)

    def test_ceiling_escalates_with_hysteresis_and_names_rule(self):
        mon = self._mon("occ<=0.9", critical_after=3)
        trs = mon.feed(_snap(t=1, occ=0.95))
        assert mon.state == "degraded"
        assert trs[0]["rule"] == "occ<=0.9" and trs[0]["rank"] == 0
        assert trs[0]["metric"] == "occ" and trs[0]["value"] == 0.95
        mon.feed(_snap(t=2, occ=0.95))
        assert mon.state == "degraded"     # 2 breaches < critical_after
        trs = mon.feed(_snap(t=3, occ=0.95))
        assert mon.state == "critical"
        assert trs[0]["to"] == "critical"
        assert trs[0]["rule"] == "occ<=0.9"
        v = mon.verdict()
        assert v["state"] == "critical"
        assert v["firing"][0]["rule"] == "occ<=0.9"

    def test_hysteresis_across_ok_degraded_boundary(self):
        """Alternating breach/clear at the boundary: recover_after=2
        means ONE clean snapshot does not recover, and the cleared
        streak means re-breaching restarts the escalation count — the
        state flaps at degraded without ever reaching critical."""
        mon = self._mon("occ<=0.9", critical_after=3, recover_after=2)
        states = []
        for i, occ in enumerate((0.95, 0.5, 0.95, 0.5, 0.95, 0.5)):
            mon.feed(_snap(t=i, occ=occ))
            states.append(mon.state)
        assert states == ["degraded"] * 6      # never critical
        # two consecutive clean snapshots DO recover
        mon.feed(_snap(t=10, occ=0.5))
        mon.feed(_snap(t=11, occ=0.5))
        assert mon.state == "ok"
        # and the recovery transition names what recovered
        rec = mon.transitions[-1]
        assert rec["from"] == "degraded" and rec["to"] == "ok"
        assert rec["rule"] == "occ<=0.9"

    def test_drift_fires_on_collapse_not_on_single_snapshot(self):
        mon = self._mon("drift(sps)@k=3,floor=0.1")
        # single-snapshot window: nothing to compare against — no fire
        mon.feed(_snap(t=0, sps=100.0))
        assert mon.state == "ok"
        for i in range(1, 6):
            mon.feed(_snap(t=i, sps=100.0 + (i % 2)))
        assert mon.state == "ok"
        mon.feed(_snap(t=9, sps=40.0))     # sustained-collapse sample
        assert mon.state == "degraded"
        tr = mon.transitions[-1]
        assert tr["rule"] == "drift:sps" and tr["value"] == 40.0

    def test_drift_ignores_jitter_within_the_gate(self):
        mon = self._mon("drift(sps)@k=3,floor=0.10")
        for i, v in enumerate((100, 101, 99, 100, 98, 97, 99, 96)):
            mon.feed(_snap(t=i, sps=float(v)))
        assert mon.state == "ok"

    def test_growth_monotone_rss_fires_dips_do_not(self):
        mon = self._mon("growth(rss)@window=4,grow=0.02")
        for i, v in enumerate((100, 110, 120, 130, 140)):
            mon.feed(_snap(t=i, rss=float(v)))
        assert mon.state == "degraded"     # monotone +40% over window
        mon2 = self._mon("growth(rss)@window=4,grow=0.02")
        for i, v in enumerate((100, 110, 105, 130, 140)):
            mon2.feed(_snap(t=i, rss=float(v)))   # a dip breaks it
        assert mon2.state == "ok"

    def test_absent_metric_neither_breaches_nor_clears(self):
        """Snapshots from another source (no such metric) must not
        recover a firing rule — recovery needs evidence."""
        mon = self._mon("occ<=0.9", recover_after=1)
        mon.feed(_snap(t=1, occ=0.95))
        assert mon.state == "degraded"
        for i in range(2, 6):
            mon.feed(_snap(t=i, source="other", unrelated=1.0))
        assert mon.state == "degraded"

    def test_all_ranks_missing_snapshots(self):
        """A log with failure events but NO snapshots at all: the
        monitor degrades on the failure and stays there (nothing can
        clear it), and the verdict is well-formed."""
        mon = health.HealthMonitor([])
        mon.feed({"event": "worker_failure", "rank": 2, "time": 1.0})
        assert mon.state == "degraded"
        v = mon.verdict()
        assert v["snapshots"] == 0
        assert v["firing"][0]["rule"] == health.FAILURE_RULE
        assert v["firing"][0]["rank"] == 2

    def test_failure_event_then_snapshots_recover(self):
        mon = health.HealthMonitor([], recover_after=2)
        mon.feed({"event": "worker_failure", "rank": 1, "time": 1.0})
        assert mon.state == "degraded"
        # attempt-level exit (no rank) degrades the rank-None stream;
        # ANY snapshot clears it — a reporting world came back
        mon.feed({"event": "elastic_worker_exit", "time": 1.5,
                  "exitcode": 43})
        mon.feed(_snap(rank=1, t=2.0, steps=1))
        mon.feed(_snap(rank=1, t=3.0, steps=2))
        assert mon.state == "ok"
        froms = [t["from"] for t in mon.transitions]
        tos = [t["to"] for t in mon.transitions]
        assert ("ok", "degraded") == (froms[0], tos[0])
        assert ("degraded", "ok") == (froms[-1], tos[-1])

    def test_giveup_is_critical(self):
        mon = health.HealthMonitor([])
        mon.feed({"event": "elastic_giveup", "time": 1.0})
        assert mon.state == "critical"

    def test_transitions_emitted_as_events_pass_validators(self,
                                                           tmp_path):
        log = tmp_path / "h.jsonl"
        mon = health.HealthMonitor(
            health.parse_rules("occ<=0.9"), emit_path=str(log))
        mon.feed(_snap(t=1, occ=0.95))
        recs, bad = export.read_log(str(log))
        assert bad == []
        (rec,) = recs
        assert rec["event"] == "health_transition"
        assert rec["to"] == "degraded" and rec["rule"] == "occ<=0.9"
        assert rec["rank"] == 0
        assert export.check_log(recs, bad) == []

    def test_per_rank_streams_are_independent(self):
        mon = self._mon("occ<=0.9", recover_after=1)
        mon.feed(_snap(rank=0, t=1, occ=0.95))
        mon.feed(_snap(rank=1, t=2, occ=0.5))
        # rank 1's clean snapshot must not recover rank 0's breach
        assert mon.state == "degraded"
        assert mon.firing()[0]["rank"] == 0


class TestLogFollower:
    def test_incremental_poll_and_torn_line_buffering(self, tmp_path):
        log = tmp_path / "f.jsonl"
        mon = health.HealthMonitor(health.parse_rules("occ<=0.9"))
        f = health.LogFollower(str(log), mon)
        assert f.poll() == []              # missing file: no crash
        line1 = json.dumps(_snap(t=1, occ=0.95)) + "\n"
        line2 = json.dumps(_snap(t=2, occ=0.95))
        with open(log, "w") as fh:
            fh.write(line1 + line2[:10])   # torn second line
        trs = f.poll()
        assert [t["to"] for t in trs] == ["degraded"]
        assert mon.snapshots_seen == 1     # the torn line is buffered
        with open(log, "a") as fh:
            fh.write(line2[10:] + "\n")
        f.poll()
        assert mon.snapshots_seen == 2


# ---------------------------------------------------------------------------
# obs/detect.py small-sample IQR degeneracy (satellite)
# ---------------------------------------------------------------------------


def _mk_span(name, rank, t0, dur_s, span_id):
    return {"event": "trace_span", "name": name, "trace_id": None,
            "span_id": span_id, "parent_id": None, "t0_wall": t0,
            "dur_ns": int(dur_s * 1e9), "rank": rank, "pid": 1000 + rank,
            "tid": "MainThread"}


class TestDetectSmallSamples:
    def test_single_observation_per_rank_no_crash(self):
        """n=1 duration per rank: summarize's IQR is 0 by construction;
        the straggler fence still works off the across-rank spread."""
        spans = [_mk_span("comm:allreduce", r, 100.0, d, f"{r}.0")
                 for r, d in enumerate((0.010, 0.010, 0.011, 0.080))]
        found = detect.stragglers(export.collect_spans(spans))
        assert [f["rank"] for f in found] == [3]

    def test_two_ranks_cannot_outvote_each_other(self):
        """n_ranks=2 degeneracy, pinned: with one peer there is no
        spread to build a leave-one-out fence from (a single-point
        "IQR" is 0, which would flag ANY gap), so stragglers() skips
        ops seen on fewer than three ranks.  Two ranks never produce
        a straggler verdict; the caller needs n_ranks >= 3 for an
        outlier to be meaningful."""
        spans = []
        for r, d in ((0, 0.010), (1, 1.0)):    # 100x apart
            spans += [_mk_span("comm:allreduce", r, 100.0 + i, d,
                               f"{r}.{i}") for i in range(4)]
        assert detect.stragglers(export.collect_spans(spans)) == []

    def test_three_ranks_is_the_minimum_meaningful_world(self):
        spans = []
        for r, d in ((0, 0.010), (1, 0.0101), (2, 0.9)):
            spans += [_mk_span("comm:allreduce", r, 100.0 + i, d,
                               f"{r}.{i}") for i in range(4)]
        found = detect.stragglers(export.collect_spans(spans))
        assert [f["rank"] for f in found] == [2]

    def test_two_observations_per_rank_iqr_degeneracy(self):
        """n=2 samples per rank: the per-rank median interpolates the
        midpoint — still a finite, crash-free summary feeding the
        across-rank fence."""
        spans = []
        for r in range(3):
            d = 0.010 if r < 2 else 0.050
            spans += [_mk_span("comm:allreduce", r, 100.0 + i, d + i * 1e-4,
                               f"{r}.{i}") for i in range(2)]
        found = detect.stragglers(export.collect_spans(spans))
        assert [f["rank"] for f in found] == [2]


# ---------------------------------------------------------------------------
# the dpxmon CLI
# ---------------------------------------------------------------------------


class TestDpxmonCli:
    def _write(self, path, recs):
        with open(path, "w") as f:
            for r in recs:
                f.write(json.dumps(r) + "\n")

    def test_replay_clean_log_exits_zero(self, tmp_path, capsys):
        from tools import dpxmon as cli
        log = tmp_path / "clean.jsonl"
        self._write(log, [_snap(rank=r, t=10.0 + i, step=i,
                                **{"train.steps": i})
                          for i in range(4) for r in (0, 1)])
        assert cli.main(["replay", str(log)]) == 0
        out = capsys.readouterr().out
        assert "health: OK" in out and "train.steps" in out

    def test_replay_seeded_violation_exits_one(self, tmp_path, capsys):
        """A pinned SLO violation (pool occupancy over the default
        saturation ceiling for the whole window) must escalate to
        CRITICAL and exit 1 — the soak gate can fail."""
        from tools import dpxmon as cli
        log = tmp_path / "bad.jsonl"
        self._write(log, [_snap(t=10.0 + i, step=i,
                                **{"serve.pool_occupancy": 0.999})
                          for i in range(5)])
        assert cli.main(["replay", str(log)]) == 1
        out = capsys.readouterr().out
        assert "critical" in out.lower()

    def test_replay_reports_recovery_with_attribution(self, tmp_path,
                                                      capsys):
        from tools import dpxmon as cli
        log = tmp_path / "rec.jsonl"
        recs = [_snap(rank=1, t=10.0, step=0, **{"train.steps": 0}),
                {"event": "worker_failure", "rank": 1, "time": 11.0,
                 "op": "allreduce", "exitcode": 43},
                _snap(rank=1, t=12.0, step=1, **{"train.steps": 1}),
                _snap(rank=1, t=13.0, step=2, **{"train.steps": 2})]
        self._write(log, recs)
        assert cli.main(["replay", str(log)]) == 0
        out = capsys.readouterr().out
        assert "worker-failure" in out     # rule attribution
        assert "degraded" in out and "ok" in out

    def test_check_flags_invalid_snapshots(self, tmp_path, capsys):
        from tools import dpxmon as cli
        log = tmp_path / "invalid.jsonl"
        bad = _snap(v=1.0)
        bad.pop("rank")
        self._write(log, [bad])
        assert cli.main(["check", str(log)]) == 1
        good = tmp_path / "good.jsonl"
        self._write(good, [_snap(v=1.0)])
        assert cli.main(["check", str(good)]) == 0
        # replay also fails on validation issues, even when healthy
        assert cli.main(["replay", str(log)]) == 1

    def test_custom_rules_flag(self, tmp_path):
        from tools import dpxmon as cli
        log = tmp_path / "r.jsonl"
        self._write(log, [_snap(t=10.0 + i, step=i, lat=50.0)
                          for i in range(5)])
        assert cli.main(["replay", str(log)]) == 0
        assert cli.main(["replay", str(log), "--rules",
                         "lat<=10"]) == 1

    def test_follow_max_seconds(self, tmp_path, capsys):
        from tools import dpxmon as cli
        log = tmp_path / "live.jsonl"
        self._write(log, [_snap(t=10.0, step=0, v=1.0)])
        rc = cli.main(["follow", str(log), "--interval", "0.05",
                       "--max-seconds", "0.2"])
        assert rc == 0
        assert "health: OK" in capsys.readouterr().out
