"""Schedules/grad transforms, KV-cache generation, and device prefetch."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_pytorch_tpu import models, optim
from distributed_pytorch_tpu.data import (DataLoader, DummyDataset,
                                          PrefetchLoader, device_prefetch)
from distributed_pytorch_tpu.models.generate import (decode_step,
                                                     make_generate_fn,
                                                     prefill)


# ---------------------------------------------------------------------------
# schedules / transforms
# ---------------------------------------------------------------------------


class TestSchedules:
    def test_cosine_endpoints(self):
        s = optim.cosine_decay(1.0, 100)
        assert float(s(0)) == pytest.approx(1.0)
        assert float(s(100)) == pytest.approx(0.0, abs=1e-6)
        assert 0.4 < float(s(50)) < 0.6

    def test_warmup_cosine_shape(self):
        s = optim.warmup_cosine(2.0, warmup_steps=10, total_steps=110)
        assert float(s(0)) == pytest.approx(0.2)     # (0+1)/10 * 2
        assert float(s(9)) == pytest.approx(2.0)
        assert float(s(10)) == pytest.approx(2.0, rel=1e-3)  # decay start
        assert float(s(110)) == pytest.approx(0.0, abs=1e-6)

    def test_with_schedule_matches_fixed_lr_adamw(self):
        """A constant schedule must reproduce the plain optimizer exactly
        (the delta-scaling trick is exact for lr-linear updates)."""
        params = {"w": jnp.ones((4, 4)), "b": jnp.zeros((4,))}
        grads = {"w": jnp.full((4, 4), 0.5), "b": jnp.ones((4,))}
        plain = optim.adamw(3e-3)
        sched = optim.with_schedule(optim.adamw, optim.constant(3e-3))
        ps, ss = params, sched.init(params)
        pp, sp = params, plain.init(params)
        for _ in range(3):
            ps, ss = sched.update(grads, ss, ps)
            pp, sp = plain.update(grads, sp, pp)
        for k in params:
            np.testing.assert_allclose(np.asarray(ps[k]), np.asarray(pp[k]),
                                       rtol=1e-6)

    def test_with_schedule_scales_step(self):
        params = {"w": jnp.zeros((2,))}
        grads = {"w": jnp.ones((2,))}
        sched = optim.with_schedule(
            optim.sgd, lambda step: jnp.where(step < 1, 1.0, 0.0))
        p, s = params, sched.init(params)
        p, s = sched.update(grads, s, p)
        moved = float(p["w"][0])
        p, s = sched.update(grads, s, p)
        assert float(p["w"][0]) == pytest.approx(moved)  # lr 0: no move

    def test_clipping(self):
        g = {"a": jnp.full((3,), 4.0), "b": jnp.full((4,), 3.0)}
        # global norm = sqrt(3*16 + 4*9) = sqrt(84)
        clipped = optim.clip_by_global_norm(g, 1.0)
        n = float(optim.schedules.global_norm(clipped))
        assert n == pytest.approx(1.0, rel=1e-5)
        same = optim.clip_by_global_norm(g, 100.0)
        np.testing.assert_allclose(np.asarray(same["a"]), 4.0)

    def test_with_clipping_wraps(self):
        opt = optim.with_clipping(optim.sgd(1.0), max_norm=1.0)
        p = {"w": jnp.zeros((4,))}
        st = opt.init(p)
        p2, _ = opt.update({"w": jnp.full((4,), 10.0)}, st, p)
        assert float(jnp.linalg.norm(p2["w"])) == pytest.approx(1.0, rel=1e-5)

    def test_accumulate_matches_big_batch(self):
        """k micro-steps with accumulation == one step on the mean grad."""
        params = {"w": jnp.ones((4,))}
        micro = [{"w": jnp.full((4,), float(i))} for i in range(1, 4)]
        mean = {"w": jnp.full((4,), 2.0)}

        inner = optim.adamw(1e-2)
        acc = optim.accumulate(optim.adamw(1e-2), every=3)
        pa, sa = params, acc.init(params)
        for g in micro:
            pa, sa = acc.update(g, sa, pa)
        pb, sb = inner.update(mean, inner.init(params), params)
        np.testing.assert_allclose(np.asarray(pa["w"]), np.asarray(pb["w"]),
                                   rtol=1e-6)

    def test_accumulate_passthrough_between_applies(self):
        acc = optim.accumulate(optim.sgd(1.0), every=2)
        p = {"w": jnp.zeros((2,))}
        s = acc.init(p)
        p1, s = acc.update({"w": jnp.ones((2,))}, s, p)
        np.testing.assert_allclose(np.asarray(p1["w"]), 0.0)  # no apply yet
        p2, s = acc.update({"w": jnp.ones((2,))}, s, p1)
        np.testing.assert_allclose(np.asarray(p2["w"]), -1.0)  # mean grad 1


# ---------------------------------------------------------------------------
# KV-cache generation
# ---------------------------------------------------------------------------


def _lm():
    return models.TransformerLM(vocab=61, dim=32, n_layers=2, n_heads=4,
                                max_seq=64)


class TestEma:
    def test_tracks_hand_rolled_average(self):
        opt = optim.with_ema(optim.sgd(0.5), decay=0.9)
        params = {"w": jnp.asarray([2.0, -1.0], jnp.float32)}
        st = opt.init(params)
        ema_ref = np.asarray(params["w"], np.float64)
        p = params
        for i in range(5):
            g = {"w": jnp.asarray([0.1 * (i + 1), -0.2], jnp.float32)}
            p, st = opt.update(g, st, p)
            ema_ref = 0.9 * ema_ref + 0.1 * np.asarray(p["w"])
        got = optim.ema_params(st)
        np.testing.assert_allclose(np.asarray(got["w"]), ema_ref,
                                   rtol=1e-6)
        # inner sgd really applied: params moved
        assert not np.allclose(np.asarray(p["w"]), [2.0, -1.0])

    def test_constant_trajectory_is_identity(self):
        """Params-initialized EMA is unbiased by construction: if the
        params never move, the extracted average IS the params at every
        step — no init transient, no correction factor (regression for
        the Adam-style debias that scaled a convex combination by
        1/(1-d^t) and returned garbage early weights)."""
        opt = optim.with_ema(optim.sgd(0.0), decay=0.999)  # lr 0: frozen
        params = {"w": jnp.asarray([3.0, -2.0], jnp.float32)}
        st = opt.init(params)
        p = params
        for _ in range(3):
            p, st = opt.update({"w": jnp.zeros(2, jnp.float32)}, st, p)
            np.testing.assert_allclose(
                np.asarray(optim.ema_params(st)["w"]),
                np.asarray(params["w"]), rtol=1e-6)

    def test_decay_validated(self):
        with pytest.raises(ValueError, match="decay"):
            optim.with_ema(optim.sgd(0.1), decay=1.0)
        with pytest.raises(ValueError, match="decay"):
            optim.with_ema(optim.sgd(0.1), decay=-0.1)

    def test_nested_extraction_and_like_cast(self):
        base = optim.with_ema(optim.adamw(1e-2), decay=0.5)
        opt = optim.with_clipping(base, 1.0)
        params = {"w": jnp.ones((4,), jnp.bfloat16)}
        st = opt.init(params)
        p, st = opt.update({"w": jnp.ones((4,), jnp.bfloat16)}, st, params)
        out = optim.ema_params(st, like=p)
        assert out["w"].dtype == jnp.bfloat16
        with pytest.raises(ValueError, match="no EmaState"):
            optim.ema_params(optim.adamw(1e-2).init(params))

    def test_ema_state_shards_under_fsdp_specs(self):
        from jax.sharding import PartitionSpec as P
        from distributed_pytorch_tpu.parallel import fsdp_param_specs
        from distributed_pytorch_tpu.parallel.fsdp import opt_state_specs

        params = {"w": jnp.zeros((64, 64), jnp.float32)}
        p_specs = fsdp_param_specs(params, 8, min_size=1)
        st = optim.with_ema(optim.adamw(1e-3)).init(params)
        o = opt_state_specs(st, p_specs, params=params)
        assert o.ema == p_specs            # the average shards like params
        # inner AdamW moments shard too; its step counter replicates
        inner_leaves = jax.tree_util.tree_leaves(
            o.inner, is_leaf=lambda x: isinstance(x, P))
        assert p_specs["w"] in inner_leaves and P() in inner_leaves

    def test_donating_first_step_no_buffer_aliasing(self):
        """Regression: with_ema/with_master_f32 init must COPY leaves
        that are already f32 — an aliased leaf makes a donating step's
        first call donate the same buffer twice and crash."""
        from distributed_pytorch_tpu.ops.losses import cross_entropy
        from distributed_pytorch_tpu.parallel import make_train_step

        model = models.DummyModel(in_dim=1, hidden_dim=8, n_classes=4)
        x = np.zeros((4, 1), np.float32)
        y = np.zeros((4,), np.int32)

        def loss_fn(p, batch):
            bx, by = batch
            return cross_entropy(model.apply(p, bx), by), {}

        for wrap in (lambda o: optim.with_ema(o, 0.9),
                     optim.with_master_f32):
            opt = wrap(optim.adamw(1e-2))
            params = jax.device_put(model.init(jax.random.PRNGKey(0)))
            st = opt.init(params)
            step = make_train_step(loss_fn, opt, donate=True)
            out = step(params, st, (x, y))       # must not crash
            jax.block_until_ready(out.loss)

    def test_inside_jitted_train_step(self):
        from distributed_pytorch_tpu.ops.losses import cross_entropy
        from distributed_pytorch_tpu.parallel import make_train_step

        model = models.DummyModel(in_dim=1, hidden_dim=8, n_classes=4)
        opt = optim.with_ema(optim.adamw(1e-2), decay=0.9)
        params = model.init(jax.random.PRNGKey(0))
        st = opt.init(params)

        def loss_fn(p, batch):
            x, y = batch
            return cross_entropy(model.apply(p, x), y), {}

        step = make_train_step(loss_fn, opt, donate=False)
        x = np.random.default_rng(0).random((8, 1), np.float32)
        y = np.zeros((8,), np.int32)
        for _ in range(3):
            params, st, loss, _ = step(params, st, (x, y))
        avg = optim.ema_params(st, like=params)
        out = model.apply(avg, jnp.asarray(x))   # usable weights
        assert out.shape == (8, 4)


class TestGenerate:
    def test_decode_matches_full_forward(self):
        """Greedy cached decoding must equal argmax over the full
        (uncached) forward at every step."""
        model = _lm()
        params = model.init(jax.random.PRNGKey(0))
        prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 7), 0, 61)
        gen = jax.jit(make_generate_fn(model, max_new=6))
        out = np.asarray(gen(params, prompt, jax.random.PRNGKey(2)))

        # reference: repeatedly run the full model
        toks = np.asarray(prompt)
        want = []
        for _ in range(6):
            logits = model.apply(params, jnp.asarray(toks))
            nxt = np.asarray(jnp.argmax(logits[:, -1], axis=-1))
            want.append(nxt)
            toks = np.concatenate([toks, nxt[:, None]], axis=1)
        np.testing.assert_array_equal(out, np.stack(want, axis=1))

    def test_prefill_then_decode_cache_consistency(self):
        model = _lm()
        params = model.init(jax.random.PRNGKey(0))
        prompt = jax.random.randint(jax.random.PRNGKey(3), (1, 5), 0, 61)
        logits, cache = prefill(model, params, prompt, max_len=16)
        assert int(cache.length) == 5
        full = model.apply(params, prompt)
        np.testing.assert_allclose(np.asarray(logits),
                                   np.asarray(full[:, -1]), atol=1e-5)
        nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        logits2, cache = decode_step(model, params, cache, nxt)
        assert int(cache.length) == 6
        full2 = model.apply(params, jnp.concatenate(
            [prompt, nxt[:, None]], axis=1))
        np.testing.assert_allclose(np.asarray(logits2),
                                   np.asarray(full2[:, -1]), atol=1e-5)

    def test_sampling_modes(self):
        model = _lm()
        params = model.init(jax.random.PRNGKey(0))
        prompt = jnp.zeros((2, 3), jnp.int32)
        out = make_generate_fn(model, 5, temperature=1.0, top_k=8)(
            params, prompt, jax.random.PRNGKey(4))
        assert out.shape == (2, 5)
        assert np.all((np.asarray(out) >= 0) & (np.asarray(out) < 61))

    def test_max_seq_guard(self):
        model = _lm()
        params = model.init(jax.random.PRNGKey(0))
        with pytest.raises(ValueError, match="max_seq"):
            make_generate_fn(model, 100)(params, jnp.zeros((1, 10), jnp.int32),
                                         jax.random.PRNGKey(0))

    def test_short_max_len_guard(self):
        """An explicit max_len too small for prompt+max_new must raise,
        not silently wrap the cache."""
        model = _lm()
        params = model.init(jax.random.PRNGKey(0))
        with pytest.raises(ValueError, match="cannot hold"):
            make_generate_fn(model, 6, max_len=8)(
                params, jnp.zeros((1, 5), jnp.int32), jax.random.PRNGKey(0))

    def test_flash_attn_model_generates(self):
        """Flash-built models pass the dense-equivalence check and decode
        to the same greedy tokens as the dense-core model."""
        from distributed_pytorch_tpu.ops import make_flash_attn_fn
        dense = _lm()
        flash = models.TransformerLM(vocab=61, dim=32, n_layers=2,
                                     n_heads=4, max_seq=64,
                                     attn_fn=make_flash_attn_fn(16, 16, min_seq_flash=None))
        params = dense.init(jax.random.PRNGKey(0))
        prompt = jax.random.randint(jax.random.PRNGKey(5), (1, 6), 0, 61)
        a = make_generate_fn(dense, 5)(params, prompt, jax.random.PRNGKey(6))
        b = make_generate_fn(flash, 5)(params, prompt, jax.random.PRNGKey(6))
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_custom_attn_rejected(self):
        def weird(q, k, v, *, causal=False, scale=None):
            return v
        model = models.TransformerLM(vocab=61, dim=32, n_layers=1,
                                     n_heads=4, max_seq=64, attn_fn=weird)
        with pytest.raises(ValueError, match="custom attn_fn"):
            make_generate_fn(model, 2)
        make_generate_fn(model, 2, allow_custom_attn=True)  # escape hatch

    def test_single_token_generate(self):
        model = _lm()
        params = model.init(jax.random.PRNGKey(0))
        prompt = jnp.zeros((2, 3), jnp.int32)
        out = make_generate_fn(model, 1)(params, prompt,
                                         jax.random.PRNGKey(0))
        assert out.shape == (2, 1)
        full = model.apply(params, prompt)
        np.testing.assert_array_equal(
            np.asarray(out[:, 0]),
            np.asarray(jnp.argmax(full[:, -1], axis=-1)))

    @pytest.mark.parametrize("prompt_len", [4, 20])
    def test_windowed_model_rolling_cache_decode(self, prompt_len):
        """A sliding-window model decodes through the rolling O(window)
        cache: greedy tokens equal the no-cache reference (full forward
        through the SAME windowed model each step), for prompts shorter
        and longer than the window, with generation running far past
        it."""
        from distributed_pytorch_tpu.models.generate import prefill
        from distributed_pytorch_tpu.ops import make_flash_attn_fn
        W = 8
        model = models.TransformerLM(
            vocab=64, dim=32, n_layers=2, n_heads=4, n_kv_heads=2,
            pos="rope", max_seq=64,
            attn_fn=make_flash_attn_fn(window=W, block_q=4, block_k=4,
                                       min_seq_flash=None))
        params = model.init(jax.random.PRNGKey(0))
        rng = np.random.default_rng(0)
        prompt = jnp.asarray(
            rng.integers(0, 64, (2, prompt_len)).astype(np.int32))
        max_new = 24
        gen = jax.jit(make_generate_fn(model, max_new))(
            params, prompt, jax.random.PRNGKey(1))

        # greedy-equivalence via ONE teacher-forced forward: the model
        # is causal, so logits at position prompt_len-1+t of the full
        # (prompt ++ gen) sequence equal the step-t logits of the
        # sequential no-cache loop — gen is the greedy trajectory iff
        # every gen[t] argmaxes its own prefix's logits (one compile
        # instead of max_new recompiles on growing shapes)
        seq = jnp.concatenate([prompt, gen], axis=1)
        full = model.apply(params, seq)
        ref = jnp.argmax(full[:, prompt_len - 1:-1], axis=-1)
        np.testing.assert_array_equal(np.asarray(gen),
                                      np.asarray(ref.astype(jnp.int32)))

        # the cache really is O(window): W slots, not prompt+max_new
        _, cache = prefill(model, params, prompt, 64, window=W)
        assert cache.k[0].shape[2] == W

    def test_prefill_rolling_layout_prompt_exceeds_window(self):
        """A prompt LONGER than the rolling cache keeps exactly the
        last W positions, each at slot p % W — checked value-by-value
        against the unwindowed cache (same model, same keys), which is
        the layout contract the serving engine's slot pool reuses.
        Dense windowed core (flash-equivalence is proven elsewhere;
        interpret-mode pallas would only slow the layout check)."""
        from distributed_pytorch_tpu.nn.attention import dense_attention
        W, S = 8, 20

        def win_fn(q, k, v, *, causal=False, scale=None):
            return dense_attention(q, k, v, causal=causal, scale=scale,
                                   window=W)
        win_fn.window = W
        model = models.TransformerLM(
            vocab=64, dim=32, n_layers=2, n_heads=4, n_kv_heads=2,
            pos="rope", max_seq=64, attn_fn=win_fn)
        params = model.init(jax.random.PRNGKey(0))
        prompt = jax.random.randint(jax.random.PRNGKey(1), (1, S), 0, 64)
        _, rolling = prefill(model, params, prompt, 32, window=W)
        _, full = prefill(model, params, prompt, 32)
        assert rolling.k[0].shape[2] == W
        for i in range(model.n_layers):
            for j in range(W):
                p = S - 1 - ((S - 1 - j) % W)      # last W: p % W == j
                assert p >= S - W
                np.testing.assert_array_equal(
                    np.asarray(rolling.k[i][:, :, j]),
                    np.asarray(full.k[i][:, :, p]))

    def test_sample_deterministic_across_batch_positions(self):
        """_sample slot-independence (the serving-engine precondition):
        greedy is exactly row-wise, and for a fixed rng a (1, V) row
        samples the same token no matter which batch position it was
        sliced from — so per-request keys reproduce the standalone
        stream from any slot."""
        from distributed_pytorch_tpu.models.generate import _sample
        rng = np.random.default_rng(0)
        logits = jnp.asarray(rng.standard_normal((4, 61)), jnp.float32)
        # greedy: batched argmax == every row alone
        batched = _sample(logits, jax.random.PRNGKey(0), 0.0, None)
        for i in range(4):
            row = _sample(logits[i:i + 1], jax.random.PRNGKey(0), 0.0,
                          None)
            assert int(batched[i]) == int(row[0])
        # keyed sampling on a (1, V) slice: deterministic across calls
        # and across the row's original batch position
        key = jax.random.PRNGKey(7)
        f = jax.jit(lambda lg: _sample(lg, key, 0.8, 8, 0.9))
        want = int(f(logits[2:3])[0])
        for _ in range(3):
            assert int(f(logits[2:3])[0]) == want
        moved = jnp.concatenate([logits[2:3], logits[:2]])  # row 2 -> 0
        assert int(f(moved[0:1])[0]) == want

    def test_mixed_window_widths_rejected(self):
        from distributed_pytorch_tpu.ops import make_flash_attn_fn
        model = models.TransformerLM(
            vocab=61, dim=32, n_layers=2, n_heads=4, max_seq=64,
            attn_fn=make_flash_attn_fn(window=8, min_seq_flash=None))
        # forge a second block with a different width
        model.blocks[1].attn.attn_fn = make_flash_attn_fn(
            window=16, min_seq_flash=None)
        with pytest.raises(ValueError, match="disagree"):
            make_generate_fn(model, 2)


# ---------------------------------------------------------------------------
# prefetch
# ---------------------------------------------------------------------------


class TestPrefetch:
    def test_yields_all_batches_on_device(self):
        ds = DummyDataset(32, 4)
        loader = DataLoader(ds, batch_size=8)
        got = list(device_prefetch(loader, size=2))
        assert len(got) == len(loader) == 4
        x, y = got[0]
        assert isinstance(x, jax.Array) and isinstance(y, jax.Array)
        np.testing.assert_allclose(np.asarray(x)[:, 0],
                                   np.arange(8, dtype=np.float32))

    def test_error_propagates(self):
        def bad():
            yield (np.zeros(2), np.zeros(2))
            raise RuntimeError("source died")
        it = device_prefetch(bad(), size=1)
        next(it)
        with pytest.raises(RuntimeError, match="source died"):
            list(it)

    def test_prefetch_loader_epochs(self):
        ds = DummyDataset(16, 4)
        pl = PrefetchLoader(DataLoader(ds, batch_size=4), size=2)
        assert len(pl) == 4
        pl.set_epoch(1)
        for epoch_batches in (list(pl), list(pl)):  # re-iterable
            assert len(epoch_batches) == 4

    def test_abandoned_iterator_stops_worker(self):
        import threading
        ds = DummyDataset(64, 4)
        it = device_prefetch(DataLoader(ds, batch_size=1), size=1)
        next(it)
        it.close()  # generator finalizer sets the abandoned flag
        deadline = __import__("time").monotonic() + 5
        while __import__("time").monotonic() < deadline:
            if not any(t.name == "dpx-prefetch" and t.is_alive()
                       for t in threading.enumerate()):
                break
        assert not any(t.name == "dpx-prefetch" and t.is_alive()
                       for t in threading.enumerate())


def test_master_f32_rescues_bf16_training():
    """bf16 params silently drop updates smaller than ~2^-8 of the weight
    magnitude; with_master_f32 must track the f32 trajectory while raw
    bf16 stalls. Also: working params keep bf16, master state is f32."""
    from distributed_pytorch_tpu.optim import adamw, with_master_f32

    target = 1.05
    steps, lr = 300, 1e-4  # per-step update ~lr << bf16 ulp at w~1.0

    def grad_at(w):
        return jax.tree_util.tree_map(
            lambda x: 2 * (x.astype(jnp.float32) - target).astype(x.dtype),
            w)

    def train(w0, opt):
        state = opt.init(w0)
        w = w0
        for _ in range(steps):
            w, state = opt.update(grad_at(w), state, w)
        return w, state

    w0_f32 = {"w": jnp.ones((64,), jnp.float32)}
    w0_bf16 = {"w": jnp.ones((64,), jnp.bfloat16)}

    w_f32, _ = train(w0_f32, adamw(lr, weight_decay=0.0))
    w_raw, _ = train(w0_bf16, adamw(lr, weight_decay=0.0))
    w_master, st = train(w0_bf16, with_master_f32(adamw(lr,
                                                        weight_decay=0.0)))

    assert w_master["w"].dtype == jnp.bfloat16      # working dtype kept
    assert st.master["w"].dtype == jnp.float32      # master is f32

    ref = np.asarray(w_f32["w"], np.float32)
    err_raw = np.abs(np.asarray(w_raw["w"], np.float32) - ref).mean()
    err_master = np.abs(np.asarray(st.master["w"],
                                   np.float32) - ref).mean()
    moved = np.abs(ref - 1.0).mean()
    assert moved > 5e-3, "f32 reference must actually move"
    # raw bf16 lost (almost) all progress; master tracks f32 closely
    assert err_raw > 0.5 * moved, (err_raw, moved)
    assert err_master < 0.05 * moved, (err_master, moved)


def test_master_f32_composition_with_schedule():
    """with_master_f32 must wrap OUTSIDE with_schedule; the inside-out
    composition (which would silently ignore the schedule) is rejected."""
    from distributed_pytorch_tpu.optim import (adamw, constant,
                                               with_master_f32,
                                               with_schedule)

    params = {"w": jnp.ones((4,), jnp.bfloat16)}
    good = with_master_f32(with_schedule(adamw, constant(1e-3)))
    state = good.init(params)
    w, state = good.update({"w": jnp.ones((4,), jnp.bfloat16)}, state,
                           params)
    assert w["w"].dtype == jnp.bfloat16

    bad = with_schedule(lambda lr: with_master_f32(adamw(lr)),
                        constant(1e-3))
    with pytest.raises(ValueError, match="with_master_f32"):
        bad.init(params)


class TestAdafactor:
    """Adafactor (optim.adafactor): factored second moments at
    O(rows+cols), paper-faithful (Shazeer & Stern) — the means-based row/
    col factors here equal the paper's sum-based ones algebraically."""

    def _ls(self):
        params = {"w": jax.random.normal(jax.random.PRNGKey(0),
                                         (16, 32)) * 0.3,
                  "b": jnp.zeros((32,))}
        x = jax.random.normal(jax.random.PRNGKey(1), (64, 16))
        y = jax.random.normal(jax.random.PRNGKey(2), (64, 32))
        return params, lambda p: jnp.mean((x @ p["w"] + p["b"] - y) ** 2)

    def test_factored_state_shapes(self):
        params, _ = self._ls()
        st = optim.adafactor().init(params)
        vr, vc, v = st.vr, st.vc, st.v
        # tree_flatten order: b (1-D, full moment) then w (factored)
        assert v[0].shape == (32,) and vr[0].shape == (0,)
        assert vr[1].shape == (16,) and vc[1].shape == (32,)
        assert v[1].shape == (0,)
        n_state = sum(int(np.prod(a.shape)) for t in (vr, vc, v) for a in t)
        n_param = 16 * 32 + 32
        assert n_state < n_param / 5   # the memory claim, concretely

    @pytest.mark.parametrize("lr", [None, 1e-2])
    def test_descends(self, lr):
        params, loss = self._ls()
        opt = optim.adafactor(lr)
        st = opt.init(params)
        l0 = float(loss(params))
        step = jax.jit(lambda p, s: opt.update(jax.grad(loss)(p), s, p))
        for _ in range(25):
            params, st = step(params, st)
        assert float(loss(params)) < 0.8 * l0

    @pytest.mark.slow
    def test_trains_lm_jitted(self):
        from distributed_pytorch_tpu.parallel import make_train_step
        from distributed_pytorch_tpu.ops.losses import cross_entropy
        model = models.TransformerLM(vocab=61, dim=32, n_layers=2,
                                     n_heads=4, max_seq=32)
        params = model.init(jax.random.PRNGKey(0))
        toks = jax.random.randint(jax.random.PRNGKey(1), (4, 17), 0, 61)

        def loss_fn(p, t):
            return cross_entropy(model.apply(p, t[:, :-1]), t[:, 1:]), {}

        opt = optim.adafactor()
        # donate=True: distinct placeholder buffers per state leaf is part
        # of the contract (donation rejects a buffer appearing twice)
        step = make_train_step(loss_fn, opt, donate=True)
        out = step(params, opt.init(params), toks)
        l0 = float(out.loss.mean())
        for _ in range(10):
            out = step(out.params, out.opt_state, toks)
        assert float(out.loss.mean()) < l0

    def test_bf16_params_stay_bf16(self):
        params = {"w": jnp.ones((8, 8), jnp.bfloat16)}
        opt = optim.adafactor(1e-2)
        st = opt.init(params)
        g = {"w": jnp.ones((8, 8), jnp.bfloat16)}
        p2, _ = opt.update(g, st, params)
        assert p2["w"].dtype == jnp.bfloat16
        assert st.vr[0].dtype == jnp.float32


class TestTopP:
    def test_nucleus_restricts_to_smallest_prefix(self):
        """top-p keeps exactly the smallest probability-sorted prefix
        reaching the mass threshold: samples never leave the nucleus,
        and the crossing token itself stays (at least one survives)."""
        from distributed_pytorch_tpu.models.generate import _sample

        # probs ~ [0.6, 0.3, 0.08, 0.02] after softmax
        logits = jnp.log(jnp.asarray([[0.6, 0.3, 0.08, 0.02]]))
        # top_p=0.5: nucleus = {0} (0.6 crosses the threshold)
        for i in range(50):
            s = _sample(logits, jax.random.PRNGKey(i), 1.0, None, 0.5)
            assert int(s[0]) == 0
        # top_p=0.7: nucleus = {0, 1}
        seen = {int(_sample(logits, jax.random.PRNGKey(i), 1.0,
                            None, 0.7)[0]) for i in range(200)}
        assert seen == {0, 1}
        # top_p=1.0 keeps everything reachable
        seen = {int(_sample(logits, jax.random.PRNGKey(i), 1.0,
                            None, 1.0)[0]) for i in range(400)}
        assert seen == {0, 1, 2, 3}
        # tiny top_p still yields the argmax, never an empty nucleus
        s = _sample(logits, jax.random.PRNGKey(0), 1.0, None, 1e-6)
        assert int(s[0]) == 0

    def test_top_p_generate_runs(self):
        model = _lm()
        params = model.init(jax.random.PRNGKey(0))
        prompt = jnp.zeros((2, 3), jnp.int32)
        out = jax.jit(make_generate_fn(model, 4, temperature=0.8,
                                       top_p=0.9))(
            params, prompt, jax.random.PRNGKey(1))
        assert out.shape == (2, 4)


class TestAdamW8bit:
    def test_tracks_f32_adamw_with_int8_state(self):
        """Blockwise-int8 moments (log-domain second moment): params
        track exact f32 AdamW within a few percent of the total update
        while both moment code trees are stored int8 — ~1/4 the state
        bytes. The log-domain nu matters: linear codes round small
        second moments to exact zero and the sqrt(0)+eps denominator
        explodes the step (regression guard: the tracking bound below
        fails by >4x with linear nu codes)."""
        params = {"w": jnp.ones((300, 7), jnp.float32),
                  "b": jnp.zeros((5,), jnp.float32)}
        opt8 = optim.adamw_8bit(1e-2)
        optf = optim.adamw(1e-2)
        s8, sf = opt8.init(params), optf.init(params)
        p8, pf = params, params
        rng = np.random.default_rng(0)
        for _ in range(10):
            g = {"w": jnp.asarray(rng.standard_normal((300, 7)),
                                  jnp.float32) * 0.1,
                 "b": jnp.asarray(rng.standard_normal(5),
                                  jnp.float32) * 0.1}
            p8, s8 = jax.jit(opt8.update)(g, s8, p8)
            pf, sf = jax.jit(optf.update)(g, sf, pf)
        assert s8.mu["w"].q.dtype == jnp.int8
        assert s8.nu["w"].q.dtype == jnp.int8
        # one f32 scale per 256 elements, not per element
        assert s8.mu["w"].scale.size == -(-params["w"].size // 256)
        for k in params:
            diff = np.abs(np.asarray(p8[k]) - np.asarray(pf[k])).max()
            total = np.abs(np.asarray(pf[k] - params[k])).max()
            assert diff < 0.1 * max(total, 1e-6), (k, diff, total)
