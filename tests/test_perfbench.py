"""perfbench unit tests: the statistical policy (median/IQR/spread
gate, including the structural withhold path), the versioned record
schema (round-trip through the trajectory store, rejection of malformed
lines and of the null-metric failure mode it exists to forbid),
last_good carry-forward selection, and seeded regression detection
through both trajectory.diff and the tools/benchdiff.py CLI (which must
exit nonzero on a >=10% synthetic regression — the CI contract)."""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from distributed_pytorch_tpu.perfbench import (  # noqa: E402
    errors, record, stats, trajectory)


# ---------------------------------------------------------------------------
# stats: median / IQR / spread-gate math
# ---------------------------------------------------------------------------


def test_summarize_median_iqr_exact():
    st = stats.summarize([10.0, 20.0, 30.0, 40.0, 50.0], warmup=0,
                         max_spread=10.0)
    assert st.median == 30.0
    assert st.q25 == 20.0 and st.q75 == 40.0
    assert st.iqr == 20.0
    assert st.spread_frac == pytest.approx(20.0 / 30.0)
    assert st.range_frac == pytest.approx(40.0 / 30.0)
    assert st.n == 5


def test_summarize_warmup_discard_excludes_cold_trial():
    # the r05 artifact shape: cold 621.6, warm ~900
    st = stats.summarize([621.6, 900.0, 905.0, 895.0, 902.0], warmup=1,
                         max_spread=0.15)
    assert st.warmup_discarded == (621.6,)
    assert 621.6 not in st.runs
    assert st.trusted
    assert st.median == pytest.approx(901.0)


def test_summarize_never_discards_everything():
    st = stats.summarize([100.0, 101.0], warmup=5, max_spread=0.15)
    assert st.runs == (101.0,)          # warmup capped at len-1
    assert st.warmup_discarded == (100.0,)
    assert not st.trusted               # 1 < MIN_TRUSTED_TRIALS
    assert "too few trials" in st.untrusted_reason


def test_spread_gate_marks_untrusted_with_reason():
    # the r05 CPU-baseline shape: ~70% spread must fail a 15% gate
    st = stats.summarize([100.0, 60.0, 100.0, 140.0, 101.0, 170.0],
                         warmup=1, max_spread=0.15)
    assert not st.trusted
    assert "exceeds gate" in st.untrusted_reason
    quiet = stats.summarize([100.0, 99.0, 101.0, 100.5], warmup=0,
                            max_spread=0.15)
    assert quiet.trusted and quiet.untrusted_reason is None


def test_summarize_empty_raises():
    with pytest.raises(ValueError):
        stats.summarize([])


def test_measure_runs_warmup_plus_trials():
    calls = []

    def thunk():
        calls.append(1)
        return 100.0 + len(calls)  # slight monotone drift, tiny spread

    st = stats.measure(thunk, trials=4, warmup=2, max_spread=0.15)
    assert len(calls) == 6
    assert len(st.warmup_discarded) == 2 and st.n == 4
    assert st.trusted


def test_measure_until_ages_out_mode_switch():
    """A contention mode switch early in the run must age out of the
    sliding window: the first full window straddles both modes (fails
    the gate), later windows sit entirely in the quiet mode."""
    seq = iter([500.0, 200.0, 210.0, 100.0, 101.0, 99.0, 100.5, 100.2])
    st = stats.measure_until(lambda: next(seq), trials=4, warmup=1,
                             max_spread=0.15, budget_s=60.0)
    assert st.trusted
    # first window (200, 210, 100, 101) straddles the modes and fails;
    # one more sample ages 200 out and the window converges
    assert st.runs == (100.0, 101.0, 99.0, 100.5)
    # everything before the converged window is visible, chronological
    assert st.warmup_discarded == (500.0, 200.0, 210.0)


def test_measure_until_budget_returns_untrusted_not_hang():
    """On a host that never goes quiet the budget bounds wall clock and
    the result is honestly untrusted — never laundered to trusted."""
    state = {"n": 0}

    def noisy():
        state["n"] += 1
        return 100.0 if state["n"] % 2 else 200.0

    st = stats.measure_until(noisy, trials=3, warmup=1, max_spread=0.15,
                             budget_s=0.2)
    assert not st.trusted
    assert "no stationary window" in st.untrusted_reason


def test_gated_ratio_withholds_on_untrusted_side():
    noisy = stats.summarize([100.0, 60.0, 140.0, 170.0], warmup=0,
                            max_spread=0.15)
    quiet = stats.summarize([100.0, 99.0, 101.0, 100.0], warmup=0,
                            max_spread=0.15)
    ratio, why = stats.gated_ratio(200.0, noisy)
    assert ratio is None and "denominator untrusted" in why
    ratio, why = stats.gated_ratio(noisy, quiet)
    assert ratio is None and "numerator untrusted" in why
    ratio, why = stats.gated_ratio(200.0, quiet)
    assert ratio == pytest.approx(2.0) and why is None
    ratio, why = stats.gated_ratio(None, quiet)
    assert ratio is None and "missing" in why


# ---------------------------------------------------------------------------
# record: schema round-trip + rejection
# ---------------------------------------------------------------------------


def _measured_record(value=0.42, metric_value=100.0, spread=0.02):
    rec = record.make_record("transformer_lm_mfu_single_chip",
                             "mfu_fraction", device="test-chip")
    rec["value"] = value
    rec["provenance"] = "measured"
    rec["trusted"] = True
    rec.pop("untrusted_reason", None)
    st = stats.summarize(
        [metric_value * (1 + spread * f) for f in (-1, -0.5, 0, 0.5, 1)],
        warmup=0, max_spread=0.15)
    rec["metrics"]["dp8_steps_per_sec"] = record.make_metric(
        None, "steps_per_sec", stats=st)
    return rec


def test_record_roundtrip_through_store(tmp_path):
    rec = _measured_record()
    assert record.validate_record(rec) == []
    store = str(tmp_path / "traj.jsonl")
    assert record.append_row(store, "bench_record", rec, ok=True,
                             wall_s=1.2)
    rows, malformed = record.iter_rows(store)
    assert malformed == []
    assert len(rows) == 1
    assert rows[0]["stage"] == "bench_record" and rows[0]["ok"] is True
    assert rows[0]["result"] == rec     # bit-identical round trip
    assert record.validate_record(rows[0]["result"]) == []


def test_validate_rejects_null_metric_value():
    """A null metric is the round-3 failure mode the schema forbids."""
    rec = _measured_record()
    rec["metrics"]["dp8_steps_per_sec"]["value"] = None
    issues = record.validate_record(rec, strict=False)
    assert any("dp8_steps_per_sec" in i and "value" in i for i in issues)
    with pytest.raises(errors.RecordInvalid) as ei:
        record.validate_record(rec)
    assert "dp8_steps_per_sec" in ei.value.field


def test_validate_unmeasured_forbids_value_requires_error():
    rec = record.make_record("m", "u")
    issues = record.validate_record(rec, strict=False)
    assert any(i.startswith("error:") for i in issues)  # must say why
    rec["error"] = "no healthy TPU backend after retries"
    assert record.validate_record(rec) == []
    rec["value"] = 0.3                  # null-ish headline smuggling
    issues = record.validate_record(rec, strict=False)
    assert any("must be ABSENT" in i for i in issues)


def test_validate_last_good_requires_source_detail():
    rec = _measured_record()
    rec["provenance"] = "last_good"
    issues = record.validate_record(rec, strict=False)
    assert any("last_good" in i for i in issues)
    rec["last_good"] = {"stage": "bench_mfu", "ts": "2026-01-01",
                        "source": "benchmarks/tpu_results.jsonl"}
    assert record.validate_record(rec) == []


def test_vs_baseline_cannot_coexist_with_withheld():
    rec = _measured_record()
    rec["vs_baseline"] = 2.0
    assert record.validate_record(rec) == []
    rec["vs_baseline_withheld"] = "also withheld??"
    issues = record.validate_record(rec, strict=False)
    assert any("must not coexist" in i for i in issues)


def test_untrusted_requires_reason():
    rec = _measured_record()
    rec["trusted"] = False
    issues = record.validate_record(rec, strict=False)
    assert any("untrusted_reason" in i for i in issues)


def test_iter_rows_surfaces_malformed_lines(tmp_path):
    store = tmp_path / "traj.jsonl"
    store.write_text('{"stage": "ok_row", "ok": true}\n'
                     'not json at all\n'
                     '[1, 2, 3]\n'
                     '\n'
                     '{"stage": "ok_row2", "ok": true}\n')
    rows, malformed = record.iter_rows(str(store))
    assert [r["stage"] for r in rows] == ["ok_row", "ok_row2"]
    assert [(n, r.split(":")[0]) for n, r in malformed] == [
        (2, "not valid JSON"), (3, "not a JSON object")]
    with pytest.raises(errors.RecordInvalid) as ei:
        record.iter_rows(str(store), strict=True)
    assert ei.value.line == 2


def test_env_fingerprint_digest_tracks_registry(monkeypatch):
    fp1 = record.env_fingerprint()
    assert "digest" in fp1 and fp1["python"]
    monkeypatch.setenv("DPX_BENCH_TRIALS", "7")
    fp2 = record.env_fingerprint()
    assert fp2["vars"]["DPX_BENCH_TRIALS"] == "7"
    assert fp2["digest"] != fp1["digest"]


# ---------------------------------------------------------------------------
# trajectory: last_good carry-forward selection
# ---------------------------------------------------------------------------


def _store(tmp_path, rows):
    p = tmp_path / "traj.jsonl"
    p.write_text("".join(json.dumps(r) + "\n" for r in rows))
    return str(p)


def test_last_good_flagship_selection(tmp_path):
    path = _store(tmp_path, [
        # usable but older — a NEWER good row must win
        {"stage": "bench_mfu", "ok": True, "ts": "t1",
         "result": {"mfu": 0.30, "tokens_per_sec": 1000.0}},
        # retracted: never a carry-forward source
        {"stage": "bench_mfu", "ok": True, "retracted": "artifact",
         "ts": "t2", "result": {"mfu": 7.42}},
        # failed row
        {"stage": "bench_mfu", "ok": False, "ts": "t3",
         "result": {"error": "wedged"}},
        # medium arm must never leak into the flagship headline
        {"stage": "bench_mfu_medium", "ok": True, "ts": "t4",
         "result": {"mfu": 0.55}},
        # a carry-forward must never be carried forward again
        {"stage": "bench_record", "ok": True, "ts": "t5",
         "result": {"metric": "transformer_lm_mfu_single_chip",
                    "value": 0.31, "provenance": "last_good"}},
        # the winner
        {"stage": "bench_mfu", "ok": True, "ts": "t6",
         "result": {"mfu": 0.33, "tokens_per_sec": 1100.0}},
        # gate-poisoned record (roofline-implausible): never evidence
        {"stage": "bench_record", "ok": True, "ts": "t7",
         "result": {"metric": "transformer_lm_mfu_single_chip",
                    "value": 0.95, "provenance": "measured",
                    "trusted": False,
                    "untrusted_reason": "exceeds roofline ceiling"}},
        # raw row with a physically impossible MFU fraction (the r02
        # "7.42" dispatch artifact) — the universal <=1 bound rejects it
        {"stage": "bench_mfu", "ok": True, "ts": "t8",
         "result": {"mfu": 7.42, "tokens_per_sec": 9e9}},
    ])
    lg = trajectory.last_good_flagship(path)
    assert lg["mfu"] == 0.33 and lg["ts"] == "t6"
    assert lg["stage"] == "bench_mfu"
    assert lg["source"] == path    # the store actually read, verbatim


def test_last_good_empty_when_nothing_usable(tmp_path):
    path = _store(tmp_path, [
        {"stage": "bench_mfu", "ok": True, "retracted": "r",
         "result": {"mfu": 0.3}},
        {"stage": "bench_dp8", "ok": True, "result": {"steps_per_sec": 9}},
    ])
    assert trajectory.last_good_flagship(path) == {}
    assert trajectory.last_good_flagship(str(tmp_path / "missing")) == {}


# ---------------------------------------------------------------------------
# trajectory.diff: seeded regression detection
# ---------------------------------------------------------------------------


def _baseline_rows(value=100.0, spread=0.02, metric="dp8_steps_per_sec",
                   direction="higher"):
    rec = record.make_record("m", "u")
    rec.update(value=0.4, provenance="measured", trusted=True)
    rec.pop("untrusted_reason", None)
    rec["metrics"] = {metric: {
        "value": value, "unit": "steps_per_sec", "provenance": "measured",
        "direction": direction, "trusted": True,
        "spread_frac": spread,
        "trials": {"runs": [value], "median": value, "spread_frac": spread,
                   "n_trials": 5},
    }}
    return [{"stage": "bench_record", "ok": True, "ts": "t1",
             "result": rec}]


def _new_record(value, spread=0.02, metric="dp8_steps_per_sec",
                direction="higher", trusted=True):
    rec = record.make_record("m", "u")
    rec.update(value=0.4, provenance="measured", trusted=True)
    rec.pop("untrusted_reason", None)
    blob = {"value": value, "unit": "steps_per_sec",
            "provenance": "measured", "direction": direction,
            "trusted": trusted, "spread_frac": spread}
    if not trusted:
        blob["untrusted_reason"] = "spread 40% exceeds gate 15%"
    rec["metrics"] = {metric: blob}
    return rec


def test_diff_flags_significant_regression(tmp_path):
    rows = _baseline_rows(100.0, spread=0.02)
    rep = trajectory.diff(_new_record(85.0), rows, min_drop=0.10)
    assert not rep.ok and len(rep.regressions) == 1
    r = rep.regressions[0]
    assert r["metric"] == "dp8_steps_per_sec"
    assert r["baseline"] == 100.0 and r["measured"] == 85.0
    assert "BENCH REGRESSION" in rep.format()
    with pytest.raises(errors.BenchRegression) as ei:
        rep.raise_first()
    assert ei.value.metric == "dp8_steps_per_sec"
    assert ei.value.drop_frac == pytest.approx(0.15)


def test_diff_change_within_gate_is_unchanged():
    rows = _baseline_rows(100.0, spread=0.02)
    rep = trajectory.diff(_new_record(95.0), rows, min_drop=0.10)
    assert rep.ok and len(rep.unchanged) == 1
    rep = trajectory.diff(_new_record(115.0), rows, min_drop=0.10)
    assert rep.ok and len(rep.improvements) == 1


def test_diff_gate_widens_with_spread():
    """A noisy baseline widens the gate: the same 15% drop that fails a
    2%-spread baseline passes a 20%-spread one."""
    rep = trajectory.diff(_new_record(85.0),
                          _baseline_rows(100.0, spread=0.20),
                          min_drop=0.10)
    assert rep.ok and len(rep.unchanged) == 1


def test_diff_lower_is_better_direction():
    rows = _baseline_rows(100.0, metric="ckpt_save_ms", direction="lower")
    worse = _new_record(120.0, metric="ckpt_save_ms", direction="lower")
    rep = trajectory.diff(worse, rows, min_drop=0.10)
    assert not rep.ok
    better = _new_record(80.0, metric="ckpt_save_ms", direction="lower")
    rep = trajectory.diff(better, rows, min_drop=0.10)
    assert rep.ok and len(rep.improvements) == 1


def test_diff_untrusted_sides_never_produce_verdicts():
    rows = _baseline_rows(100.0)
    rep = trajectory.diff(_new_record(40.0, trusted=False), rows,
                          min_drop=0.10)
    assert rep.ok                       # a 60% "drop" on an untrusted side
    assert rep.skipped and "not comparable" in rep.skipped[0][1]
    rep = trajectory.diff(_new_record(40.0, metric="never_seen"), rows,
                          min_drop=0.10)
    assert rep.ok and "no trusted measured baseline" in rep.skipped[0][1]


def test_diff_zero_baseline_is_skipped_not_crash():
    rep = trajectory.diff(_new_record(40.0), _baseline_rows(0.0),
                          min_drop=0.10)
    assert rep.ok and "baseline value is 0" in rep.skipped[0][1]


def test_diff_malformed_blob_reason_is_not_carry_forward():
    rec = _new_record(40.0)
    rec["metrics"]["dp8_steps_per_sec"] = 123      # not a dict
    rep = trajectory.diff(rec, _baseline_rows(100.0), min_drop=0.10)
    assert rep.ok and "malformed metric blob" in rep.skipped[0][1]


def test_single_observation_blob_is_untrusted():
    """A measured blob without trials detail carries no spread — it must
    not anchor or receive regression verdicts with a zero-width gate
    (the r05 single-rep 2x-swing class)."""
    blob = record.make_metric(0.42, "mfu_fraction")
    assert blob["trusted"] is False
    assert "single observation" in blob["untrusted_reason"]
    assert record.validate_metric_blob("m", blob) == []
    # a carry-forward blob keeps the trust of its traceable source
    lg = record.make_metric(0.42, "mfu_fraction", provenance="last_good",
                            last_good={"stage": "bench_mfu", "ts": "t"})
    assert lg["trusted"] is True
    # and diff() lists the single-rep side as skipped, attributed
    rec = _new_record(100.0)
    rec["metrics"]["dp8_steps_per_sec"] = record.make_metric(
        100.0, "steps_per_sec")
    rep = trajectory.diff(rec, _baseline_rows(200.0), min_drop=0.10)
    assert rep.ok and "single observation" in rep.skipped[0][1]


# ---------------------------------------------------------------------------
# tools/benchdiff.py CLI: the CI contract
# ---------------------------------------------------------------------------


def _run_benchdiff(*args):
    return subprocess.run(
        [sys.executable, "-m", "tools.benchdiff", *args],
        capture_output=True, text=True, timeout=60, cwd=REPO)


def test_benchdiff_exits_nonzero_on_injected_regression(tmp_path):
    """The acceptance contract: a synthetic >=10% regression makes the
    CLI exit nonzero with an attributed report."""
    store = _store(tmp_path, _baseline_rows(100.0, spread=0.02))
    rec_file = tmp_path / "new.json"
    rec_file.write_text(json.dumps(_new_record(88.0)))   # -12% drop
    out = _run_benchdiff("--log", store, "--record", str(rec_file))
    assert out.returncode == 1, out.stdout + out.stderr
    assert "BENCH REGRESSION" in out.stdout
    assert "dp8_steps_per_sec" in out.stdout


def test_benchdiff_clean_and_self_diff_exit_zero(tmp_path):
    store = _store(tmp_path, _baseline_rows(100.0, spread=0.02))
    rec_file = tmp_path / "new.json"
    rec_file.write_text(json.dumps(_new_record(101.0)))
    out = _run_benchdiff("--log", store, "--record", str(rec_file))
    assert out.returncode == 0, out.stdout + out.stderr
    # no --record: newest stored schema record vs the rows before it
    rows = (_baseline_rows(100.0)
            + [{"stage": "bench_record", "ok": True, "ts": "t2",
                "result": _new_record(99.0)}])
    out = _run_benchdiff("--log", _store(tmp_path, rows))
    assert out.returncode == 0, out.stdout + out.stderr
    assert json.loads(out.stdout.strip().splitlines()[-1])["unchanged"] == 1


def test_diff_anchors_on_ok_false_record_metrics(tmp_path):
    """Row-level ok gates only the last_good carry-forward. A record
    whose flagship was unmeasured logs ok=false, but its trusted
    measured metrics (the only fresh numbers when the tunnel is wedged)
    must still anchor baselines AND be selected as the new side in
    store mode — otherwise the CI benchdiff step is vacuous on a
    TPU-less container."""
    base = _baseline_rows(100.0, spread=0.02)
    base[0]["ok"] = False                      # unmeasured flagship
    series = trajectory.metric_series(base)
    assert series["dp8_steps_per_sec"][0]["value"] == 100.0

    rows = base + [{"stage": "bench_record", "ok": False, "ts": "t2",
                    "result": _new_record(85.0)}]   # -15% drop
    out = _run_benchdiff("--log", _store(tmp_path, rows),
                         "--min-drop", "0.10")
    assert out.returncode == 1, out.stdout + out.stderr
    assert "BENCH REGRESSION" in out.stdout


def test_benchdiff_strict_rejects_corrupt_store(tmp_path):
    store = tmp_path / "traj.jsonl"
    store.write_text(json.dumps(_baseline_rows(100.0)[0]) + "\n"
                     + "CORRUPT LINE\n")
    out = _run_benchdiff("--log", str(store), "--strict")
    assert out.returncode == 2
    assert "line 2" in out.stderr
    # non-strict: skipped with a comment, diff proceeds
    rec_file = tmp_path / "new.json"
    rec_file.write_text(json.dumps(_new_record(101.0)))
    out = _run_benchdiff("--log", str(store), "--record", str(rec_file))
    assert out.returncode == 0
    assert "malformed store line 2" in out.stderr


def test_benchdiff_record_mode_excludes_its_own_store_row(tmp_path):
    """bench.py self-logs its record by default — --record mode must not
    diff the record against its own store row (0% forever)."""
    new = _new_record(85.0)                            # -15% vs 100
    rows = _baseline_rows(100.0, spread=0.02) \
        + [{"stage": "bench_record", "ok": True, "ts": "t2",
            "result": new}]
    rec_file = tmp_path / "new.json"
    rec_file.write_text(json.dumps(new))
    out = _run_benchdiff("--log", _store(tmp_path, rows),
                         "--record", str(rec_file), "--min-drop", "0.10")
    assert out.returncode == 1, out.stdout + out.stderr
    assert "BENCH REGRESSION" in out.stdout


def test_report_reader_stays_jax_free(tmp_path):
    """run_all_tpu's watcher shells out to report.py on a 60s budget
    BECAUSE report is jax-free and cannot hang on a wedged tunnel; the
    perfbench-backed store reader must keep that invariant (private
    file-based load — the real package __init__ pulls jax)."""
    _store(tmp_path, _baseline_rows(100.0))
    code = (
        "import sys; sys.path.insert(0, %r); sys.path.insert(0, %r)\n"
        "import report\n"
        "rows, mal = report.load_rows_checked(%r)\n"
        "assert len(rows) == 1 and not mal\n"
        "assert report.newest_schema_record(rows) is not None\n"
        "assert 'jax' not in sys.modules, 'report pulled jax'\n"
        "assert 'distributed_pytorch_tpu' not in sys.modules, "
        "'report imported (or shadowed) the real package'\n"
        % (REPO, os.path.join(REPO, "benchmarks"),
           str(tmp_path / "traj.jsonl")))
    out = subprocess.run([sys.executable, "-c", code],
                         capture_output=True, text=True, timeout=60,
                         env={k: v for k, v in os.environ.items()
                              if k != "PALLAS_AXON_POOL_IPS"})
    assert out.returncode == 0, out.stdout + out.stderr


def test_benchdiff_empty_store_is_not_a_failure(tmp_path):
    out = _run_benchdiff("--log", str(tmp_path / "missing.jsonl"))
    assert out.returncode == 0
    assert "nothing to compare" in out.stdout


def test_benchdiff_runs_against_committed_trajectory():
    """The CI invocation: the committed store must parse (strict) and
    carry no regression verdict."""
    out = _run_benchdiff("--strict")
    assert out.returncode == 0, out.stdout + out.stderr
