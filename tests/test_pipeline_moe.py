"""Pipeline (pp) and expert (ep) parallelism tests: schedule correctness vs
unpipelined execution, differentiability, MoE routing invariants, and
ep-sharded training."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

import distributed_pytorch_tpu as dist
from distributed_pytorch_tpu import models, optim
from distributed_pytorch_tpu.nn.attention import TransformerBlock
from distributed_pytorch_tpu.ops.losses import cross_entropy_per_example
from distributed_pytorch_tpu.parallel import (make_gspmd_pipeline_fn,
                                              make_spmd_train_step,
                                              shard_batch_spec,
                                              stack_layer_params)
from distributed_pytorch_tpu.parallel.moe import MoELayer
from distributed_pytorch_tpu.parallel.tensor import shard_params
from distributed_pytorch_tpu.runtime import context


def _mlp_stage_fn(block):
    """stage_fn running a (layers_per_stage,)-stacked slice of identical
    blocks over one microbatch."""
    def stage_fn(stacked, x):
        n = jax.tree_util.tree_leaves(stacked)[0].shape[0]
        for i in range(n):
            layer = jax.tree_util.tree_map(lambda p: p[i], stacked)
            x = block.apply(layer, x)
        return x
    return stage_fn


def test_pipeline_matches_sequential():
    """4-stage pipeline over 8 layers == running the 8 layers in order."""
    mesh = context.init_mesh(pp=4, dp=2)
    try:
        block = TransformerBlock(dim=16, n_heads=2, causal=True)
        keys = jax.random.split(jax.random.PRNGKey(0), 8)
        layers = [block.init(k) for k in keys]
        stacked = stack_layer_params(layers)
        stacked = shard_params(
            stacked, jax.tree_util.tree_map(lambda _: P("pp"), stacked),
            mesh)

        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.standard_normal((8, 4, 16)), jnp.float32)

        pipe = make_gspmd_pipeline_fn(mesh, _mlp_stage_fn(block),
                                      n_microbatches=4)
        got = jax.jit(pipe)(stacked, x)

        want = x
        for lp in layers:
            want = block.apply(lp, want)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-4, atol=2e-5)
    finally:
        dist.cleanup()


def test_pipeline_backward_trains():
    """Gradients flow through the pipeline schedule (autodiffed GPipe)."""
    mesh = context.init_mesh(pp=4, dp=2)
    try:
        block = TransformerBlock(dim=8, n_heads=2, causal=True)
        layers = [block.init(k)
                  for k in jax.random.split(jax.random.PRNGKey(0), 4)]
        stacked = stack_layer_params(layers)
        pipe = make_gspmd_pipeline_fn(mesh, _mlp_stage_fn(block),
                                      n_microbatches=2)

        def loss(stacked, x):
            return jnp.mean(pipe(stacked, x) ** 2)

        x = jnp.ones((4, 2, 8))
        g = jax.jit(jax.grad(loss))(stacked, x)
        norms = [float(jnp.linalg.norm(l))
                 for l in jax.tree_util.tree_leaves(g)]
        assert all(np.isfinite(norms))
        assert any(n > 0 for n in norms)
    finally:
        dist.cleanup()


def test_moe_layer_routing_invariants():
    """Every kept token's output is its expert's FFN of it, weighted by its
    gate prob; with ample capacity nothing is dropped."""
    layer = MoELayer(dim=8, n_experts=4, mlp_ratio=2, capacity_factor=4.0)
    params = layer.init(jax.random.PRNGKey(0))
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((16, 8)), jnp.float32)
    y, aux = layer.apply(params, x)
    assert y.shape == x.shape
    assert float(aux) > 0

    # manual reference: route each token to argmax expert, full capacity
    import math
    logits = np.asarray(x @ params["gate"]["w"])
    probs = np.exp(logits - logits.max(-1, keepdims=True))
    probs /= probs.sum(-1, keepdims=True)
    want = np.zeros_like(np.asarray(x))
    for i in range(16):
        e = int(np.argmax(probs[i]))
        h = np.asarray(x[i]) @ np.asarray(params["fc1"]["w"][e]) + \
            np.asarray(params["fc1"]["b"][e])
        h = np.asarray(jax.nn.gelu(jnp.asarray(h)))
        o = h @ np.asarray(params["fc2"]["w"][e]) + \
            np.asarray(params["fc2"]["b"][e])
        want[i] = probs[i, e] * o
    np.testing.assert_allclose(np.asarray(y), want, rtol=2e-3, atol=2e-4)


def test_moe_capacity_drops_overflow():
    """With capacity 1 and all tokens routed to one expert, only one token
    gets output; the rest are dropped (zero)."""
    layer = MoELayer(dim=4, n_experts=2, capacity_factor=0.125)  # cap=1
    params = layer.init(jax.random.PRNGKey(0))
    x = jnp.tile(jnp.asarray([[1.0, 2.0, 3.0, 4.0]]), (16, 1))
    y, _ = layer.apply(params, x)
    nonzero = np.abs(np.asarray(y)).sum(-1) > 1e-9
    assert nonzero.sum() == 1


def test_moe_lm_ep_sharded_training():
    """MoETransformerLM trains under a dp x tp x ep mesh with experts
    sharded over ep; loss decreases and expert params stay ep-sharded."""
    mesh = context.init_mesh(dp=2, tp=2, ep=2)
    try:
        model = models.MoETransformerLM(vocab=32, dim=16, n_layers=2,
                                        n_heads=2, n_experts=2, max_seq=8,
                                        capacity_factor=4.0)
        params = shard_params(model.init(jax.random.PRNGKey(0)),
                              model.param_specs(), mesh)
        opt = optim.adamw(1e-3)
        opt_state = opt.init(params)

        def loss_fn(p, batch):
            x, y = batch
            logits, aux = model.apply(p, x)
            return cross_entropy_per_example(logits, y).mean() + 0.01 * aux, {}

        step = make_spmd_train_step(loss_fn, opt, donate=False)
        rng = np.random.default_rng(0)
        toks = rng.integers(0, 32, (8, 8)).astype(np.int32)
        batch = shard_batch_spec((toks, toks), mesh, P("dp", None))

        losses = []
        out = step(params, opt_state, batch)
        losses.append(float(out.loss))
        for _ in range(4):
            out = step(out.params, out.opt_state, batch)
            losses.append(float(out.loss))
        assert losses[-1] < losses[0]
        fc1 = out.params["blocks"][0]["moe"]["fc1"]["w"]
        # trailing Nones normalize away in PartitionSpec
        assert fc1.sharding.spec == P("ep")
    finally:
        dist.cleanup()
