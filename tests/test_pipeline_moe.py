"""Pipeline (pp) and expert (ep) parallelism tests: schedule correctness vs
unpipelined execution, differentiability, MoE routing invariants, and
ep-sharded training."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

import distributed_pytorch_tpu as dist
from distributed_pytorch_tpu import models, optim
from distributed_pytorch_tpu.nn.attention import TransformerBlock
from distributed_pytorch_tpu.ops.losses import cross_entropy_per_example
from distributed_pytorch_tpu.parallel import (make_gspmd_pipeline_fn,
                                              make_spmd_train_step,
                                              shard_batch_spec,
                                              stack_layer_params)
from distributed_pytorch_tpu.parallel.moe import MoELayer
from distributed_pytorch_tpu.parallel.tensor import shard_params
from distributed_pytorch_tpu.runtime import context


def _mlp_stage_fn(block):
    """stage_fn running a (layers_per_stage,)-stacked slice of identical
    blocks over one microbatch."""
    def stage_fn(stacked, x):
        n = jax.tree_util.tree_leaves(stacked)[0].shape[0]
        for i in range(n):
            layer = jax.tree_util.tree_map(lambda p: p[i], stacked)
            x = block.apply(layer, x)
        return x
    return stage_fn


def test_pipeline_matches_sequential():
    """4-stage pipeline over 8 layers == running the 8 layers in order."""
    mesh = context.init_mesh(pp=4, dp=2)
    try:
        block = TransformerBlock(dim=16, n_heads=2, causal=True)
        keys = jax.random.split(jax.random.PRNGKey(0), 8)
        layers = [block.init(k) for k in keys]
        stacked = stack_layer_params(layers)
        stacked = shard_params(
            stacked, jax.tree_util.tree_map(lambda _: P("pp"), stacked),
            mesh)

        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.standard_normal((8, 4, 16)), jnp.float32)

        pipe = make_gspmd_pipeline_fn(mesh, _mlp_stage_fn(block),
                                      n_microbatches=4)
        got = jax.jit(pipe)(stacked, x)

        want = x
        for lp in layers:
            want = block.apply(lp, want)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-4, atol=2e-5)
    finally:
        dist.cleanup()


@pytest.mark.slow
def test_pipeline_backward_trains():
    """Gradients flow through the pipeline schedule (autodiffed GPipe)."""
    mesh = context.init_mesh(pp=4, dp=2)
    try:
        block = TransformerBlock(dim=8, n_heads=2, causal=True)
        layers = [block.init(k)
                  for k in jax.random.split(jax.random.PRNGKey(0), 4)]
        stacked = stack_layer_params(layers)
        pipe = make_gspmd_pipeline_fn(mesh, _mlp_stage_fn(block),
                                      n_microbatches=2)

        def loss(stacked, x):
            return jnp.mean(pipe(stacked, x) ** 2)

        x = jnp.ones((4, 2, 8))
        g = jax.jit(jax.grad(loss))(stacked, x)
        norms = [float(jnp.linalg.norm(l))
                 for l in jax.tree_util.tree_leaves(g)]
        assert all(np.isfinite(norms))
        assert any(n > 0 for n in norms)
    finally:
        dist.cleanup()


@pytest.mark.slow
def test_moe_layer_routing_invariants():
    """Every kept token's output is its expert's FFN of it, weighted by its
    gate prob; with ample capacity nothing is dropped."""
    layer = MoELayer(dim=8, n_experts=4, mlp_ratio=2, capacity_factor=4.0)
    params = layer.init(jax.random.PRNGKey(0))
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((16, 8)), jnp.float32)
    y, aux = layer.apply(params, x)
    assert y.shape == x.shape
    assert float(aux) > 0

    # manual reference: route each token to argmax expert, full capacity
    import math
    logits = np.asarray(x @ params["gate"]["w"])
    probs = np.exp(logits - logits.max(-1, keepdims=True))
    probs /= probs.sum(-1, keepdims=True)
    want = np.zeros_like(np.asarray(x))
    for i in range(16):
        e = int(np.argmax(probs[i]))
        h = np.asarray(x[i]) @ np.asarray(params["fc1"]["w"][e]) + \
            np.asarray(params["fc1"]["b"][e])
        h = np.asarray(jax.nn.gelu(jnp.asarray(h)))
        o = h @ np.asarray(params["fc2"]["w"][e]) + \
            np.asarray(params["fc2"]["b"][e])
        want[i] = probs[i, e] * o
    np.testing.assert_allclose(np.asarray(y), want, rtol=2e-3, atol=2e-4)


@pytest.mark.slow
def test_moe_capacity_drops_overflow():
    """With capacity 1 and all tokens routed to one expert, only one token
    gets output; the rest are dropped (zero)."""
    layer = MoELayer(dim=4, n_experts=2, capacity_factor=0.125)  # cap=1
    params = layer.init(jax.random.PRNGKey(0))
    x = jnp.tile(jnp.asarray([[1.0, 2.0, 3.0, 4.0]]), (16, 1))
    y, _ = layer.apply(params, x)
    nonzero = np.abs(np.asarray(y)).sum(-1) > 1e-9
    assert nonzero.sum() == 1


@pytest.mark.slow
def test_moe_lm_ep_sharded_training():
    """MoETransformerLM trains under a dp x tp x ep mesh with experts
    sharded over ep; loss decreases and expert params stay ep-sharded."""
    mesh = context.init_mesh(dp=2, tp=2, ep=2)
    try:
        model = models.MoETransformerLM(vocab=32, dim=16, n_layers=2,
                                        n_heads=2, n_experts=2, max_seq=8,
                                        capacity_factor=4.0)
        params = shard_params(model.init(jax.random.PRNGKey(0)),
                              model.param_specs(), mesh)
        opt = optim.adamw(1e-3)
        opt_state = opt.init(params)

        def loss_fn(p, batch):
            x, y = batch
            logits, aux = model.apply(p, x)
            return cross_entropy_per_example(logits, y).mean() + 0.01 * aux, {}

        step = make_spmd_train_step(loss_fn, opt, donate=False)
        rng = np.random.default_rng(0)
        toks = rng.integers(0, 32, (8, 8)).astype(np.int32)
        batch = shard_batch_spec((toks, toks), mesh, P("dp", None))

        losses = []
        out = step(params, opt_state, batch)
        losses.append(float(out.loss))
        for _ in range(4):
            out = step(out.params, out.opt_state, batch)
            losses.append(float(out.loss))
        assert losses[-1] < losses[0]
        fc1 = out.params["blocks"][0]["moe"]["fc1"]["w"]
        # experts over ep AND expert-internal hidden over tp (the model
        # forwards tp_axis into moe_param_specs)
        assert fc1.sharding.spec == P("ep", None, "tp")
    finally:
        dist.cleanup()


# ---------------------------------------------------------------------------
# 1F1B schedule
# ---------------------------------------------------------------------------

from distributed_pytorch_tpu.parallel.pipeline import (  # noqa: E402
    _build_1f1b_schedule, make_pipeline_train_fn)
from distributed_pytorch_tpu.utils import profiler  # noqa: E402


def _per_example_mse(y, t):
    return jnp.mean((y - t) ** 2, axis=tuple(range(1, y.ndim)))


def _sequential_loss(block, layers, x, t):
    y = x
    for lp in layers:
        y = block.apply(lp, y)
    return jnp.mean(_per_example_mse(y, t))


class Test1F1BSchedule:
    @pytest.mark.parametrize("S,T", [(1, 3), (2, 2), (4, 4), (4, 11)])
    def test_schedule_tables_valid(self, S, T):
        fwd, bwd, depth = _build_1f1b_schedule(S, T)
        n_ticks = fwd.shape[0]
        for s in range(S):
            fs = [int(fwd[t, s]) for t in range(n_ticks) if fwd[t, s] >= 0]
            bs = [int(bwd[t, s]) for t in range(n_ticks) if bwd[t, s] >= 0]
            assert fs == list(range(T)), "each mb forwarded once, in order"
            assert bs == list(range(T)), "each mb backwarded once, in order"
        # causality: stage s consumes m exactly one tick after s-1 produced
        # it; cotangents likewise flow one stage per tick
        ftick = {(s, int(fwd[t, s])): t
                 for t in range(n_ticks) for s in range(S) if fwd[t, s] >= 0}
        btick = {(s, int(bwd[t, s])): t
                 for t in range(n_ticks) for s in range(S) if bwd[t, s] >= 0}
        for m in range(T):
            for s in range(1, S):
                assert ftick[(s, m)] == ftick[(s - 1, m)] + 1
                assert btick[(s - 1, m)] == btick[(s, m)] + 1
            assert btick[(S - 1, m)] == ftick[(S - 1, m)], \
                "last stage backwards its forward in the same tick"
        # the 1F1B property: ring depth bounded by S+1, independent of T
        assert depth <= S + 1

    def test_depth_independent_of_t(self):
        _, _, d8 = _build_1f1b_schedule(4, 8)
        _, _, d32 = _build_1f1b_schedule(4, 32)
        assert d8 == d32


class Test1F1BTraining:
    def _setup(self, n_layers=8, dim=16, batch=8, seq=4):
        block = TransformerBlock(dim=dim, n_heads=2, causal=True)
        keys = jax.random.split(jax.random.PRNGKey(0), n_layers)
        layers = [block.init(k) for k in keys]
        stacked = stack_layer_params(layers)
        rng = np.random.default_rng(1)
        x = jnp.asarray(rng.standard_normal((batch, seq, dim)), jnp.float32)
        t = jnp.asarray(rng.standard_normal((batch, seq, dim)), jnp.float32)
        return block, layers, stacked, x, t

    @pytest.mark.slow
    def test_1f1b_matches_sequential(self):
        mesh = context.init_mesh(pp=4, dp=2)
        try:
            block, layers, stacked, x, t = self._setup()
            fn = make_pipeline_train_fn(mesh, _mlp_stage_fn(block),
                                        _per_example_mse, 4)
            loss, grads = jax.jit(fn)(stacked, x, t)

            want_loss, want_grads = jax.value_and_grad(
                lambda st: _sequential_loss(
                    block,
                    [jax.tree_util.tree_map(lambda p: p[i], st)
                     for i in range(8)], x, t))(stacked)
            assert float(loss) == pytest.approx(float(want_loss), rel=2e-5)
            for g, w in zip(jax.tree_util.tree_leaves(grads),
                            jax.tree_util.tree_leaves(want_grads)):
                np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                           rtol=2e-4, atol=2e-5)
        finally:
            dist.cleanup()

    def test_1f1b_matches_gpipe(self):
        mesh = context.init_mesh(pp=4, dp=2)
        try:
            block, _, stacked, x, t = self._setup()
            f1 = make_pipeline_train_fn(mesh, _mlp_stage_fn(block),
                                        _per_example_mse, 4)
            f2 = make_pipeline_train_fn(mesh, _mlp_stage_fn(block),
                                        _per_example_mse, 4,
                                        schedule="gpipe")
            l1, g1 = jax.jit(f1)(stacked, x, t)
            l2, g2 = jax.jit(f2)(stacked, x, t)
            assert float(l1) == pytest.approx(float(l2), rel=2e-5)
            for a, b in zip(jax.tree_util.tree_leaves(g1),
                            jax.tree_util.tree_leaves(g2)):
                np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                           rtol=2e-4, atol=2e-5)
        finally:
            dist.cleanup()

    @pytest.mark.slow
    def test_1f1b_ragged_batch(self):
        """batch 7 with 4 microbatches: the divisibility constraint is
        relaxed via zero-weight padding; numerics match the unpadded
        sequential run."""
        mesh = context.init_mesh(pp=4, dp=2)
        try:
            block, layers, stacked, x, t = self._setup(batch=7)
            fn = make_pipeline_train_fn(mesh, _mlp_stage_fn(block),
                                        _per_example_mse, 4)
            loss, grads = jax.jit(fn)(stacked, x, t)
            want_loss, want_grads = jax.value_and_grad(
                lambda st: _sequential_loss(
                    block,
                    [jax.tree_util.tree_map(lambda p: p[i], st)
                     for i in range(8)], x, t))(stacked)
            assert float(loss) == pytest.approx(float(want_loss), rel=2e-5)
            for g, w in zip(jax.tree_util.tree_leaves(grads),
                            jax.tree_util.tree_leaves(want_grads)):
                np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                           rtol=2e-4, atol=2e-5)
        finally:
            dist.cleanup()

    def test_1f1b_activation_memory_below_gpipe(self):
        """The point of 1F1B: with many microbatches the autodiffed GPipe
        schedule stores every scan tick's activations while 1F1B keeps an
        O(S) ring, so XLA's temp-buffer high water mark must be smaller."""
        mesh = context.init_mesh(pp=4, dp=2)
        try:
            block, _, stacked, x, t = self._setup(batch=32)
            f1 = make_pipeline_train_fn(mesh, _mlp_stage_fn(block),
                                        _per_example_mse, 16)
            f2 = make_pipeline_train_fn(mesh, _mlp_stage_fn(block),
                                        _per_example_mse, 16,
                                        schedule="gpipe")
            m1 = profiler.compiled_memory(f1, stacked, x, t)
            m2 = profiler.compiled_memory(f2, stacked, x, t)
            if not m1 or not m2 or "temp_size_bytes" not in m1:
                pytest.skip("backend exposes no memory analysis")
            assert m1["temp_size_bytes"] < m2["temp_size_bytes"], (m1, m2)
        finally:
            dist.cleanup()


# ---------------------------------------------------------------------------
# top-k routing
# ---------------------------------------------------------------------------


def _expert_ffn(params, e, xi):
    h = np.asarray(xi) @ np.asarray(params["fc1"]["w"][e]) + \
        np.asarray(params["fc1"]["b"][e])
    h = np.asarray(jax.nn.gelu(jnp.asarray(h)))
    return h @ np.asarray(params["fc2"]["w"][e]) + \
        np.asarray(params["fc2"]["b"][e])


def test_moe_top2_matches_dense_mixture():
    """top_k=2 with ample capacity == for each token the renormalized
    gate-weighted sum of its two best experts' FFN outputs."""
    layer = MoELayer(dim=8, n_experts=4, mlp_ratio=2, capacity_factor=4.0,
                     top_k=2)
    params = layer.init(jax.random.PRNGKey(1))
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.standard_normal((16, 8)), jnp.float32)
    y, m = layer.apply_with_metrics(params, x)
    assert float(m["drop_rate"]) == 0.0

    logits = np.asarray(x @ params["gate"]["w"])
    probs = np.exp(logits - logits.max(-1, keepdims=True))
    probs /= probs.sum(-1, keepdims=True)
    want = np.zeros_like(np.asarray(x))
    for i in range(16):
        top2 = np.argsort(probs[i])[::-1][:2]
        g = probs[i, top2] / probs[i, top2].sum()
        for gw, e in zip(g, top2):
            want[i] += gw * _expert_ffn(params, int(e), x[i])
    np.testing.assert_allclose(np.asarray(y), want, rtol=2e-3, atol=2e-4)


def test_moe_top1_scarce_capacity_matches_switch_reference():
    """top_k=1 under SCARCE capacity must reproduce Switch routing against
    an independent numpy reference (token-order queue per expert, overflow
    dropped) — the choice-major cumsum must degenerate exactly to the
    token cumsum."""
    layer = MoELayer(dim=8, n_experts=4, mlp_ratio=2, capacity_factor=0.5)
    params = layer.init(jax.random.PRNGKey(2))
    rng = np.random.default_rng(2)
    n = 32
    x = jnp.asarray(rng.standard_normal((n, 8)), jnp.float32)
    y, m = layer.apply_with_metrics(params, x)

    cap = max(int(0.5 * n / 4), 1)
    logits = np.asarray(x @ params["gate"]["w"])
    probs = np.exp(logits - logits.max(-1, keepdims=True))
    probs /= probs.sum(-1, keepdims=True)
    want = np.zeros((n, 8), np.float32)
    counts = [0] * 4
    kept = 0
    for i in range(n):
        e = int(np.argmax(probs[i]))
        if counts[e] < cap:
            counts[e] += 1
            kept += 1
            want[i] = probs[i, e] * _expert_ffn(params, e, x[i])
    np.testing.assert_allclose(np.asarray(y), want, rtol=2e-3, atol=2e-4)
    assert float(m["drop_rate"]) == pytest.approx(1 - kept / n)


def test_moe_first_choices_have_capacity_priority():
    """Under scarcity a token's FIRST choice beats another token's second
    choice for the slot, even when the second-chooser comes earlier in
    token order: t0 = (E1 first, E0 second), t1 = (E0 first), cap 1 per
    expert -> E0's slot must go to t1, and t0 keeps only its E1 output."""
    layer = MoELayer(dim=2, n_experts=2, mlp_ratio=2, capacity_factor=0.5,
                     top_k=2)
    params = layer.init(jax.random.PRNGKey(3))
    params["gate"]["w"] = jnp.asarray([[4.0, 0.0], [0.0, 4.0]])
    t0, t1 = [1.0, 2.0], [2.0, 1.0]   # argmax experts: t0->E1, t1->E0
    x = jnp.asarray([t0, t1], jnp.float32)
    # n=2, k=2, e=2, cf=0.5 -> cap = 1 slot per expert for 4 dispatches
    y, m = layer.apply_with_metrics(params, x)
    assert float(m["drop_rate"]) == pytest.approx(0.5)
    np.testing.assert_allclose(np.asarray(m["expert_load"]), [0.5, 0.5])

    logits = np.asarray(x @ params["gate"]["w"])
    probs = np.exp(logits - logits.max(-1, keepdims=True))
    probs /= probs.sum(-1, keepdims=True)
    # renormalized over the two selected experts = original probs (e=2)
    want0 = probs[0, 1] * _expert_ffn(params, 1, x[0])  # first choice kept
    want1 = probs[1, 0] * _expert_ffn(params, 0, x[1])  # first choice kept
    # inverted priority would instead give t0 both slots and t1 nothing
    np.testing.assert_allclose(np.asarray(y[0]), want0, rtol=2e-3,
                               atol=2e-4)
    np.testing.assert_allclose(np.asarray(y[1]), want1, rtol=2e-3,
                               atol=2e-4)


def test_moe_z_loss_and_drop_metrics():
    layer = MoELayer(dim=4, n_experts=2, capacity_factor=0.125)  # cap=1
    params = layer.init(jax.random.PRNGKey(0))
    x = jnp.tile(jnp.asarray([[1.0, 2.0, 3.0, 4.0]]), (16, 1))
    _, m = layer.apply_with_metrics(params, x)
    assert float(m["z_loss"]) > 0
    # 16 identical tokens, one expert, cap 1 -> 15/16 dropped; the single
    # kept dispatch is 100% of the KEPT load on that expert
    assert float(m["drop_rate"]) == pytest.approx(15 / 16)
    np.testing.assert_allclose(np.asarray(m["expert_load"]).sum(), 1.0)


@pytest.mark.slow
def test_moe_lm_top2_trains():
    """MoETransformerLM with top_k=2 + z-loss trains under the ep mesh."""
    mesh = context.init_mesh(dp=2, tp=2, ep=2)
    try:
        model = models.MoETransformerLM(vocab=32, dim=16, n_layers=2,
                                        n_heads=2, n_experts=2, max_seq=8,
                                        capacity_factor=4.0, top_k=2)
        params = shard_params(model.init(jax.random.PRNGKey(0)),
                              model.param_specs(), mesh)
        opt = optim.adamw(1e-2)
        opt_state = opt.init(params)

        def loss_fn(p, batch):
            x, y = batch
            logits, aux = model.apply(p, x)
            return cross_entropy_per_example(logits, y).mean() + 0.01 * aux, {}

        step = make_spmd_train_step(loss_fn, opt, donate=False)
        rng = np.random.default_rng(0)
        toks = rng.integers(0, 32, (8, 8)).astype(np.int32)
        batch = shard_batch_spec((toks, toks), mesh, P("dp", None))
        losses = []
        out = step(params, opt_state, batch)
        losses.append(float(out.loss))
        for _ in range(6):
            out = step(out.params, out.opt_state, batch)
            losses.append(float(out.loss))
        assert losses[-1] < losses[0]
    finally:
        dist.cleanup()


def test_moe_lm_exposes_router_metrics():
    """The model API surfaces layer-averaged router diagnostics so
    capacity_factor/top_k can be tuned from the training loop."""
    model = models.MoETransformerLM(vocab=16, dim=8, n_layers=2, n_heads=2,
                                    n_experts=2, max_seq=8, top_k=2,
                                    capacity_factor=4.0)
    params = model.init(jax.random.PRNGKey(0))
    toks = jnp.asarray(np.arange(16).reshape(2, 8) % 16, jnp.int32)
    logits, aux, m = model.apply_with_metrics(params, toks)
    assert logits.shape == (2, 8, 16)
    assert set(m) == {"aux_loss", "z_loss", "drop_rate", "expert_load"}
    assert float(m["drop_rate"]) >= 0
    # back-compat two-tuple keeps the combined aux
    logits2, aux2 = model.apply(params, toks)
    np.testing.assert_allclose(np.asarray(logits), np.asarray(logits2))
    assert float(aux) == pytest.approx(float(aux2))


class TestExpertChoice:
    """Expert-choice routing (router='experts'): each expert takes its
    top-capacity tokens — exact load balance, no aux loss."""

    def _layer(self, **kw):
        from distributed_pytorch_tpu.parallel.moe import MoELayer
        return MoELayer(dim=8, n_experts=4, mlp_ratio=2,
                        capacity_factor=1.0, router="experts", **kw)

    def test_exact_balance_and_zero_aux(self):
        layer = self._layer()
        params = layer.init(jax.random.PRNGKey(0))
        x = jax.random.normal(jax.random.PRNGKey(1), (32, 8))
        y, m = layer.apply_with_metrics(params, x)
        assert y.shape == x.shape
        np.testing.assert_allclose(np.asarray(m["expert_load"]), 0.25)
        assert float(m["aux_loss"]) == 0.0
        assert 0.0 <= float(m["drop_rate"]) < 1.0

    def test_unchosen_tokens_get_zero(self):
        """With capacity_factor < 1 some tokens are picked by no expert;
        their layer output must be exactly zero (residual carries them)."""
        from distributed_pytorch_tpu.parallel.moe import MoELayer
        layer = MoELayer(dim=8, n_experts=2, mlp_ratio=2,
                         capacity_factor=0.25, router="experts")
        params = layer.init(jax.random.PRNGKey(0))
        x = jax.random.normal(jax.random.PRNGKey(1), (16, 8))
        y, m = layer.apply_with_metrics(params, x)
        assert float(m["drop_rate"]) > 0.0
        # at least one token got nothing -> exact zero row
        norms = np.linalg.norm(np.asarray(y), axis=-1)
        assert (norms == 0.0).sum() >= 1

    def test_gate_values_weight_output(self):
        """Doubling one expert's gate path: output is combine-weighted by
        the softmax score of (token, expert) — check against a manual
        dense computation on a tiny case."""
        layer = self._layer()
        params = layer.init(jax.random.PRNGKey(0))
        x = jax.random.normal(jax.random.PRNGKey(2), (8, 8))
        y, _ = layer.apply_with_metrics(params, x)

        # manual: scores = softmax over experts; expert e takes top-C
        # tokens; out[n] += score[n,e] * expert_e(x[n])
        import jax.numpy as jnp
        from distributed_pytorch_tpu.nn.core import gelu
        probs = jax.nn.softmax(
            (x @ params["gate"]["w"]).astype(jnp.float32), axis=-1)
        cap = 8 // 4
        want = np.zeros((8, 8), np.float32)
        for e in range(4):
            idx = np.argsort(-np.asarray(probs[:, e]), kind="stable")[:cap]
            w1, b1 = params["fc1"]["w"][e], params["fc1"]["b"][e]
            w2, b2 = params["fc2"]["w"][e], params["fc2"]["b"][e]
            for nn_ in idx:
                h = np.asarray(gelu(x[nn_] @ w1 + b1))
                want[nn_] += float(probs[nn_, e]) * np.asarray(h @ w2 + b2)
        np.testing.assert_allclose(np.asarray(y), want, atol=1e-5)

    def test_moe_lm_expert_choice_trains(self):
        from distributed_pytorch_tpu import optim
        from distributed_pytorch_tpu.models.moe_lm import MoETransformerLM
        from distributed_pytorch_tpu.ops.losses import cross_entropy
        model = MoETransformerLM(vocab=61, dim=32, n_layers=2, n_heads=4,
                                 n_experts=2, max_seq=32, router="experts")
        params = model.init(jax.random.PRNGKey(0))
        toks = jax.random.randint(jax.random.PRNGKey(1), (2, 17), 0, 61)

        def loss_fn(p, t):
            logits, aux = model.apply(p, t[:, :-1])
            return cross_entropy(logits, t[:, 1:]) + 0.01 * aux

        opt = optim.adamw(1e-3)
        opt_state = opt.init(params)
        l0 = None
        for _ in range(6):
            loss, grads = jax.value_and_grad(loss_fn)(params, toks)
            params, opt_state = opt.update(grads, opt_state, params)
            l0 = float(loss) if l0 is None else l0
        assert float(loss) < l0

    def test_bad_router_rejected(self):
        from distributed_pytorch_tpu.parallel.moe import MoELayer
        with pytest.raises(ValueError, match="router"):
            MoELayer(dim=8, n_experts=2, router="magic")

    def test_single_expert_generous_capacity(self):
        """capacity_factor * n / e > n must clamp, not crash top_k."""
        from distributed_pytorch_tpu.parallel.moe import MoELayer
        layer = MoELayer(dim=8, n_experts=1, mlp_ratio=2,
                         capacity_factor=2.0, router="experts")
        params = layer.init(jax.random.PRNGKey(0))
        x = jax.random.normal(jax.random.PRNGKey(1), (16, 8))
        y, m = layer.apply_with_metrics(params, x)
        assert y.shape == x.shape
        assert float(m["drop_rate"]) == 0.0


class TestSharedExperts:
    def test_shared_expert_adds_dense_ffn(self):
        """With one routed expert (gate prob 1, generous capacity) the
        layer output is exactly routed_mlp(x) + shared_mlp(x): the
        shared expert is an always-on dense FFN on top of routing."""
        from distributed_pytorch_tpu.parallel.moe import MoELayer
        from distributed_pytorch_tpu.nn.core import gelu

        layer = MoELayer(dim=8, n_experts=1, mlp_ratio=2,
                         capacity_factor=4.0, n_shared_experts=2)
        params = layer.init(jax.random.PRNGKey(0))
        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.standard_normal((3, 5, 8)), jnp.float32)
        y, aux = layer.apply(params, x)

        xt = x.reshape(-1, 8)
        routed = (gelu(xt @ params["fc1"]["w"][0] + params["fc1"]["b"][0])
                  @ params["fc2"]["w"][0] + params["fc2"]["b"][0])
        shared = (gelu(xt @ params["shared"]["fc1"]["w"]
                       + params["shared"]["fc1"]["b"])
                  @ params["shared"]["fc2"]["w"]
                  + params["shared"]["fc2"]["b"])
        np.testing.assert_allclose(np.asarray(y.reshape(-1, 8)),
                                   np.asarray(routed + shared),
                                   rtol=2e-5, atol=2e-5)

        # width scales with n_shared_experts; absent when 0
        assert params["shared"]["fc1"]["w"].shape == (8, 2 * 2 * 8)
        p0 = MoELayer(dim=8, n_experts=1,
                      n_shared_experts=0).init(jax.random.PRNGKey(0))
        assert "shared" not in p0

    @pytest.mark.parametrize("router", ["tokens", "experts"])
    def test_shared_experts_ep_sharded_matches_oracle(self, router):
        """Shared experts compose with ep sharding (replicated dense FFN
        next to ep-sharded routed experts) at oracle-equal loss, for
        both routers."""
        mesh = context.init_mesh(dp=2, tp=2, ep=2)
        try:
            model = models.MoETransformerLM(
                vocab=32, dim=16, n_layers=2, n_heads=2, n_experts=2,
                max_seq=8, capacity_factor=4.0, router=router,
                n_shared_experts=1)
            p_full = model.init(jax.random.PRNGKey(0))
            params = shard_params(p_full, model.param_specs(), mesh)

            def loss_fn(p, batch):
                x, y = batch
                logits, aux = model.apply(p, x)
                return (cross_entropy_per_example(logits, y).mean()
                        + 0.01 * aux, {})

            opt = optim.adamw(1e-3)
            step = make_spmd_train_step(loss_fn, opt, donate=False)
            rng = np.random.default_rng(0)
            toks = rng.integers(0, 32, (8, 8)).astype(np.int32)
            batch = shard_batch_spec((toks, toks), mesh, P("dp", None))
            out = step(params, opt.init(params), batch)
            oracle = float(loss_fn(p_full, (toks, toks))[0])
            np.testing.assert_allclose(float(out.loss), oracle,
                                       rtol=1e-4, atol=1e-5)
        finally:
            dist.cleanup()
