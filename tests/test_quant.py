"""Weight-only int8 quantization (ops/quant.py): representation error,
tree transform, and drop-in inference through every consumer (Linear,
Embedding, tied head, MoE experts, the cached decode path)."""

import jax
import jax.numpy as jnp
import numpy as np

from distributed_pytorch_tpu import models
from distributed_pytorch_tpu.models.generate import make_generate_fn
from distributed_pytorch_tpu.ops.quant import (dequantize, quantize_int8,
                                               quantize_tree,
                                               quantized_bytes)


class TestQuantizeInt8:
    def test_roundtrip_error_bound(self):
        """Symmetric per-channel int8: error <= scale/2 = max|w|/254
        per channel."""
        w = jax.random.normal(jax.random.PRNGKey(0), (64, 128))
        q, s = quantize_int8(w)
        assert q.dtype == jnp.int8 and s.shape == (128,)
        back = dequantize(q, s, jnp.float32)
        err = np.abs(np.asarray(back - w))
        bound = np.asarray(jnp.max(jnp.abs(w), axis=0)) / 254.0 + 1e-7
        assert (err <= bound[None, :]).all()

    def test_3d_expert_weights(self):
        w = jax.random.normal(jax.random.PRNGKey(1), (4, 16, 32))
        q, s = quantize_int8(w)
        assert s.shape == (4, 32)
        back = dequantize(q, s, jnp.float32)
        np.testing.assert_allclose(np.asarray(back), np.asarray(w),
                                   atol=float(jnp.max(jnp.abs(w))) / 100)

    def test_tree_transform_selective(self):
        tree = {"big": {"w": jnp.ones((128, 64)), "b": jnp.zeros(64)},
                "tiny": {"w": jnp.ones((4, 4))},
                "ln": {"scale": jnp.ones(64)}}
        qt = quantize_tree(tree, min_size=1024)
        assert "w_q" in qt["big"] and "w" not in qt["big"]
        assert qt["big"]["b"].dtype == jnp.float32
        assert "w" in qt["tiny"]          # below min_size: untouched
        assert "scale" in qt["ln"]
        assert quantized_bytes(qt) < quantized_bytes(tree)


class TestQuantizedInference:
    def _model(self, **kw):
        return models.TransformerLM(vocab=61, dim=32, n_layers=2, n_heads=4,
                                    max_seq=32, **kw)

    def test_logits_close_and_bytes_shrink(self):
        model = self._model()
        params = model.init(jax.random.PRNGKey(0))
        qp = quantize_tree(params, min_size=256)
        assert quantized_bytes(qp) < 0.5 * quantized_bytes(params)
        toks = jax.random.randint(jax.random.PRNGKey(1), (2, 12), 0, 61)
        a = np.asarray(model.apply(params, toks))
        b = np.asarray(model.apply(qp, toks))
        # int8 weight rounding: small relative logit error
        assert np.max(np.abs(a - b)) < 0.15 * np.max(np.abs(a))

    def test_generate_runs_quantized(self):
        """The cached decode path (prefill + scanned decode, tied + GQA +
        rope) runs on a quantized tree and matches its own uncached
        argmax rollout."""
        model = self._model(tie_embeddings=True, n_kv_heads=2, pos="rope")
        qp = quantize_tree(model.init(jax.random.PRNGKey(0)), min_size=256)
        prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 5), 0, 61)
        out = np.asarray(make_generate_fn(model, 5)(
            qp, prompt, jax.random.PRNGKey(2)))
        toks = np.asarray(prompt)
        want = []
        for _ in range(5):
            logits = model.apply(qp, jnp.asarray(toks))
            nxt = np.asarray(jnp.argmax(logits[:, -1], axis=-1))
            want.append(nxt)
            toks = np.concatenate([toks, nxt[:, None]], axis=1)
        np.testing.assert_array_equal(out, np.stack(want, axis=1))

    def test_pinned_weight_stream_same_tokens(self):
        """pin_weight_stream is a scheduling hint (anti-LICM barrier in
        the decode scan, generate.py) — it must not change a single
        generated token, quantized or not."""
        model = self._model(n_kv_heads=2)
        params = model.init(jax.random.PRNGKey(0))
        qp = quantize_tree(params, min_size=256)
        prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 5), 0, 61)
        rng = jax.random.PRNGKey(2)
        for tree in (params, qp):
            plain = np.asarray(make_generate_fn(model, 6)(
                tree, prompt, rng))
            pinned = np.asarray(make_generate_fn(
                model, 6, pin_weight_stream=True)(tree, prompt, rng))
            np.testing.assert_array_equal(plain, pinned)

    def test_moe_lm_quantized_forward(self):
        from distributed_pytorch_tpu.models.moe_lm import MoETransformerLM
        model = MoETransformerLM(vocab=61, dim=32, n_layers=2, n_heads=4,
                                 n_experts=2, max_seq=32)
        params = model.init(jax.random.PRNGKey(0))
        qp = quantize_tree(params, min_size=256)
        toks = jax.random.randint(jax.random.PRNGKey(1), (2, 12), 0, 61)
        a, _ = model.apply(params, toks)
        b, _ = model.apply(qp, toks)
        assert np.isfinite(np.asarray(b)).all()
        assert np.max(np.abs(np.asarray(a - b))) < 0.25 * np.max(
            np.abs(np.asarray(a)))


def test_resnet_quantized_forward():
    """Conv weights quantize too (per spatial-and-out-channel scales) and
    ResNet18 runs on the quantized tree."""
    model = models.ResNet18(n_classes=10, small_input=True)
    params, state = model.init(jax.random.PRNGKey(0))
    qp = quantize_tree(params, min_size=256)
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 32, 32, 3))
    a, _ = model.apply(params, x, state=state, train=False)
    b, _ = model.apply(qp, x, state=state, train=False)
    assert np.isfinite(np.asarray(b)).all()
    assert np.max(np.abs(np.asarray(a - b))) < 0.25 * np.max(
        np.abs(np.asarray(a)))
