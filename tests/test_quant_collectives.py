"""Quantized collective layer (comm/wire.py + dpx_allreduce_q8 +
quantized_pmean): wire-format codec invariants, the executable ring spec
(cross-rank determinism, error bounds, byte accounting — the issue-1
acceptance criteria), error-feedback residual behavior, and the
reference-exact full-width contracts staying untouched.

The numpy ring simulation IS the native schedule (bit-for-bit — the
slow multiprocess test in test_host_backend.py pins that), so the fast
tests here exercise the real wire numerics without spawning processes.
"""

import numpy as np
import pytest

import distributed_pytorch_tpu as dist
from distributed_pytorch_tpu.comm import primitives as prim
from distributed_pytorch_tpu.comm import wire
from distributed_pytorch_tpu.ops.quant import (ErrorFeedback,
                                               dequantize_grad_blocks,
                                               quantize_grad_blocks)

MIB_ELEMS = 262144  # 1 MiB of f32 — the acceptance-criterion bucket size


def _ranks(world, n, seed=0, scale=1.0):
    rng = np.random.default_rng(seed)
    return [(rng.standard_normal(n) * scale).astype(np.float32)
            for _ in range(world)]


class TestBlockCodec:
    def test_roundtrip_error_within_one_step(self):
        x = (np.random.default_rng(0).standard_normal(8192) * 3
             ).astype(np.float32)
        q, s = wire.quantize_blocks(x)
        back = wire.dequantize_blocks(q, s)
        # per-block error <= scale/2 = amax/254
        for b in range(s.size):
            blk = slice(b * wire.QUANT_BLOCK, (b + 1) * wire.QUANT_BLOCK)
            assert np.abs(back[blk] - x[blk]).max() <= s[b] / 2 + 1e-7

    def test_small_integer_payloads_exact(self):
        """The integer-exact snap: |v| <= 127 integers round-trip
        bit-exactly (scale 1) — counters and tallies survive the wire."""
        x = np.random.default_rng(1).integers(
            -127, 128, 4096).astype(np.float32)
        q, s = wire.quantize_blocks(x)
        assert np.array_equal(s, np.ones_like(s))
        assert np.array_equal(wire.dequantize_blocks(q, s), x)

    def test_zeros_exact(self):
        q, s = wire.quantize_blocks(np.zeros(3000, np.float32))
        assert np.array_equal(wire.dequantize_blocks(q, s),
                              np.zeros(3000, np.float32))

    def test_numpy_jnp_codec_parity(self):
        """ops/quant.py's jnp quantizer (the SPMD wire) and comm/wire.py's
        numpy quantizer (the host wire) produce identical grids."""
        x = (np.random.default_rng(2).standard_normal(4 * wire.QUANT_BLOCK)
             * 2.5).astype(np.float32)
        qn, sn = wire.quantize_blocks(x)
        qj, sj = quantize_grad_blocks(x.reshape(4, wire.QUANT_BLOCK))
        assert np.array_equal(qn.reshape(4, -1), np.asarray(qj))
        assert np.array_equal(sn, np.asarray(sj).ravel())
        back_j = np.asarray(dequantize_grad_blocks(qj, sj)).ravel()
        assert np.array_equal(back_j, wire.dequantize_blocks(qn, sn))

    def test_ragged_tail(self):
        x = (np.random.default_rng(3).standard_normal(wire.QUANT_BLOCK + 77)
             ).astype(np.float32)
        q, s = wire.quantize_blocks(x)
        assert q.size == x.size and s.size == 2
        assert np.abs(wire.dequantize_blocks(q, s) - x).max() <= s.max()


class TestQuantRing:
    """The executable spec of dpx_allreduce_q8 (bit-identical to it)."""

    def test_acceptance_bytes_and_error_1mib(self):
        """ISSUE-1 acceptance: on a >= 1 MiB N(0,1) gradient bucket the
        quantized all_reduce moves >= 3.5x fewer payload bytes than the
        f32 ring, with max relative error <= 1e-2."""
        world = 2
        xs = _ranks(world, MIB_ELEMS)
        res, qbytes = wire.simulate_quant_ring(xs)
        f32bytes = wire.ring_allreduce_wire_bytes(MIB_ELEMS, world)
        assert f32bytes / qbytes >= 3.5
        assert qbytes == wire.quant_ring_allreduce_wire_bytes(
            MIB_ELEMS, world)
        exact = np.sum(np.stack(xs), axis=0, dtype=np.float64)
        err = np.abs(res[0] - exact).max() / np.abs(exact).max()
        assert err <= 1e-2, err

    def test_byte_reduction_all_worlds(self):
        for world in (2, 4, 8):
            ratio = (wire.ring_allreduce_wire_bytes(MIB_ELEMS, world)
                     / wire.quant_ring_allreduce_wire_bytes(
                         MIB_ELEMS, world))
            assert ratio >= 3.5, (world, ratio)

    def test_cross_rank_determinism(self):
        """Every rank decodes the same forwarded bytes: results are
        BIT-identical on all ranks (ranks cannot drift apart)."""
        for world in (2, 4, 8):
            res, _ = wire.simulate_quant_ring(
                _ranks(world, 3 * wire.QUANT_BLOCK + 123, seed=world))
            for r in range(1, world):
                assert np.array_equal(res[r], res[0]), (world, r)

    def test_error_grows_at_most_one_step_per_hop(self):
        """Lossy accumulation is bounded: the reduce-scatter leg
        requantizes partials once per hop, so larger worlds pay more —
        but never more than ~one quantization step of the running
        partial per traversed hop (documented bound; w=8 measured
        ~1.6e-2 on N(0,1), vs 6e-3 at w=2)."""
        for world, bound in ((2, 1e-2), (4, 1.5e-2), (8, 2.5e-2)):
            xs = _ranks(world, MIB_ELEMS // 2, seed=7)
            res, _ = wire.simulate_quant_ring(xs)
            exact = np.sum(np.stack(xs), axis=0, dtype=np.float64)
            err = np.abs(res[0] - exact).max() / np.abs(exact).max()
            assert err <= bound, (world, err)

    def test_integer_payloads_survive_the_ring(self):
        """Small-magnitude integer payloads stay integer-exact END TO
        END: every partial sum of integers is again a small integer, so
        every hop takes the snap path."""
        world = 4
        rng = np.random.default_rng(5)
        xs = [rng.integers(-10, 11, 5000).astype(np.float32)
              for _ in range(world)]
        res, _ = wire.simulate_quant_ring(xs)
        exact = np.sum(np.stack(xs), axis=0).astype(np.float32)
        assert np.array_equal(res[0], exact)

    def test_ragged_and_tiny_sizes(self):
        for n in (1, 7, wire.QUANT_BLOCK - 1, wire.QUANT_BLOCK + 1, 5000):
            res, _ = wire.simulate_quant_ring(_ranks(4, n, seed=n))
            assert res[0].size == n


class TestErrorFeedback:
    def test_residual_corrects_bias_over_steps(self):
        """Reducing the SAME gradient repeatedly with EF: the time-average
        of what crossed the wire converges to the true gradient (the
        single-shot quantization bias cancels)."""
        ef = ErrorFeedback()
        g = (np.random.default_rng(0).standard_normal(4096) * 1e-2
             ).astype(np.float32)
        outs = [ef.compensate(g) for _ in range(64)]
        single = np.abs(outs[0] - g).max()
        averaged = np.abs(np.mean(outs, axis=0) - g).max()
        assert averaged < single / 10
        # residual stays bounded by one quantization step
        q, s = wire.quantize_blocks(g)
        assert np.abs(ef.residual).max() <= s.max()

    def test_compensated_value_is_on_wire_grid(self):
        """compensate() returns the int8-grid value, so the first ring
        hop retransmits it exactly (re-quantization is idempotent)."""
        ef = ErrorFeedback()
        g = (np.random.default_rng(1).standard_normal(2048) * 3
             ).astype(np.float32)
        grid = ef.compensate(g)
        q, s = wire.quantize_blocks(grid)
        assert np.array_equal(wire.dequantize_blocks(q, s), grid)

    def test_tiny_gradients_recovered(self):
        """A gradient far below its block-mate's scale quantizes to zero
        on step 1 but MUST eventually transmit via the residual."""
        ef = ErrorFeedback()
        g = np.zeros(wire.QUANT_BLOCK, np.float32)
        g[0] = 100.0   # sets the block scale
        g[1] = 0.11    # far below scale/2 ~ 0.39: rounds to zero
        sent = np.sum([ef.compensate(g)[1] for _ in range(40)])
        assert sent > 0.0  # residual accumulated until it crossed a step


class TestSpmdQuantPath:
    """grad_reduce="quant" on the 8-device SPMD mesh (quantized_pmean)."""

    def test_quantized_pmean_error_within_1e2_w8(self, group8):
        """The SPMD quantized reduce (two quantizations total) meets the
        1e-2 acceptance bound at world=8."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P

        from distributed_pytorch_tpu.runtime.jax_compat import shard_map

        mesh = dist.get_mesh()
        xs = np.stack(_ranks(8, 65536, seed=9))

        def island(x):
            return prim.quantized_pmean(x[0], "dp")[None]

        f = shard_map(island, mesh=mesh, in_specs=(P("dp"),),
                      out_specs=P("dp"), check_vma=False)
        out = np.asarray(jax.jit(f)(jnp.asarray(xs)))
        exact = xs.mean(axis=0)
        err = np.abs(out[0] - exact).max() / np.abs(exact).max()
        assert err <= 1e-2, err

    def test_grad_reduce_quant_trains(self, group8):
        """make_train_step(grad_reduce="quant") — the issue-1 opt-in
        mode — tracks the exact-reduce step on the reference workload."""
        import jax
        from distributed_pytorch_tpu import models, optim
        from distributed_pytorch_tpu.ops.losses import cross_entropy
        from distributed_pytorch_tpu.parallel import make_train_step

        model = models.DummyModel(in_dim=1, hidden_dim=32, n_classes=4)
        params = model.init(jax.random.PRNGKey(0))
        opt = optim.adamw(1e-3)

        def loss_fn(p, batch):
            x, y = batch
            return cross_entropy(model.apply(p, x), y), {}

        x = dist.shard_batch(np.arange(16, dtype=np.float32)[:, None])
        y = dist.shard_batch((np.arange(16) % 4).astype(np.int32))
        step_q = make_train_step(loss_fn, opt, donate=False,
                                 grad_reduce="quant")
        step_e = make_train_step(loss_fn, opt, donate=False)
        pq = pe = params
        sq, se = opt.init(params), opt.init(params)
        for _ in range(5):
            oq = step_q(pq, sq, (x, y))
            oe = step_e(pe, se, (x, y))
            pq, sq, pe, se = oq.params, oq.opt_state, oe.params, oe.opt_state
        np.testing.assert_allclose(float(oq.loss.mean()),
                                   float(oe.loss.mean()),
                                   rtol=5e-3, atol=5e-3)


class TestExactContractsUntouched:
    """The reference-exact full-width contracts never quantize."""

    def test_wire_flag_validated(self, group8):
        with pytest.raises(ValueError, match="wire"):
            dist.all_reduce(np.zeros((8, 3), np.float32), wire="fp4")

    def test_reduce_and_gather_have_no_wire_param(self):
        """Rooted ops (reduce's untouched-non-root, gather's
        zeros-on-non-primary) stay reference-exact: the quantized wire is
        not even plumbed to them."""
        import inspect
        from distributed_pytorch_tpu.comm import collectives, host_backend
        for fn in (collectives.reduce, collectives.gather,
                   host_backend.reduce, host_backend.gather):
            assert "wire" not in inspect.signature(fn).parameters

    def test_integer_all_reduce_stays_exact_under_quant_wire(self, group8):
        """wire="quant" on the SPMD front door is a no-op hint: results
        stay exact (XLA moves exact bytes over ICI)."""
        import jax.numpy as jnp
        x = jnp.stack([jnp.full((3,), float(r + 1)) for r in range(8)])
        out = dist.all_reduce(x, op="sum", wire="quant")
        np.testing.assert_allclose(np.asarray(out), 36.0)


class TestByteAccounting:
    def test_quant_wire_bytes_formula(self):
        for n in (1, 1000, wire.QUANT_BLOCK, MIB_ELEMS + 13):
            nb = wire.num_blocks(n)
            assert wire.quant_wire_bytes(n) == n + 4 * nb

    def test_segment_grid_covers_everything_once(self):
        for n in (5000, MIB_ELEMS + 777):
            for world in (2, 4, 8):
                segs = wire.segment_blocks(n, world)
                assert sum(c for _, c in segs) == wire.num_blocks(n)
                starts = [s for s, _ in segs]
                assert starts == sorted(starts)

    def test_quantized_pmean_wire_bytes(self):
        assert prim.quantized_pmean_wire_bytes(MIB_ELEMS, 1) == 0
        b = prim.quantized_pmean_wire_bytes(MIB_ELEMS, 8)
        # ~4x fewer than an equivalent exact f32 exchange of both legs
        f32 = 2 * MIB_ELEMS * 4 * 7  # two legs, 7/8 of the bucket each
        assert f32 / b > 3.5
