"""Rotary position embeddings (nn/rotary.py) and their integration:
relative-phase property, model plumbing (pos="rope" drops the learned
table), cached-decode parity (the cache stores post-rotation keys), and
composition with GQA + the flash kernel."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_pytorch_tpu import models
from distributed_pytorch_tpu.models.generate import make_generate_fn
from distributed_pytorch_tpu.nn.rotary import apply_rope


class TestApplyRope:
    def test_norm_preserved(self):
        """Rotations preserve each head vector's norm."""
        x = jax.random.normal(jax.random.PRNGKey(0), (2, 4, 8, 16))
        y = apply_rope(x, jnp.arange(8))
        np.testing.assert_allclose(
            np.linalg.norm(np.asarray(x), axis=-1),
            np.linalg.norm(np.asarray(y), axis=-1), rtol=1e-5)

    def test_relative_phase(self):
        """<R(p)q, R(p')k> depends only on p - p': shifting every
        position by a constant leaves attention logits unchanged — the
        property that makes RoPE a RELATIVE scheme."""
        kq, kk = jax.random.split(jax.random.PRNGKey(1))
        q = jax.random.normal(kq, (1, 2, 6, 32))
        k = jax.random.normal(kk, (1, 2, 6, 32))
        pos = jnp.arange(6)

        def logits(q_r, k_r):
            return jnp.einsum("bhqd,bhkd->bhqk", q_r, k_r)

        base = logits(apply_rope(q, pos), apply_rope(k, pos))
        shifted = logits(apply_rope(q, pos + 37), apply_rope(k, pos + 37))
        np.testing.assert_allclose(np.asarray(base), np.asarray(shifted),
                                   atol=1e-4)

    def test_position_zero_identity(self):
        x = jax.random.normal(jax.random.PRNGKey(2), (1, 1, 1, 8))
        np.testing.assert_allclose(
            np.asarray(apply_rope(x, jnp.zeros(1, jnp.int32))),
            np.asarray(x), atol=1e-7)

    def test_odd_head_dim_rejected(self):
        with pytest.raises(ValueError, match="even"):
            apply_rope(jnp.ones((1, 1, 2, 7)), jnp.arange(2))


class TestRopeModel:
    def _model(self, **kw):
        return models.TransformerLM(vocab=61, dim=32, n_layers=2, n_heads=4,
                                    max_seq=64, pos="rope", **kw)

    def test_no_pos_table(self):
        params = self._model().init(jax.random.PRNGKey(0))
        assert "pos" not in params
        learned = models.TransformerLM(vocab=61, dim=32, n_layers=2,
                                       n_heads=4, max_seq=64)
        assert "pos" in learned.init(jax.random.PRNGKey(0))

    def test_position_sensitivity(self):
        """In a SINGLE layer without positional information, the last
        position's output is permutation-invariant over the prefix
        (keys/values come straight from content-only embeddings; with
        more layers the causal mask itself leaks position). RoPE must
        break that invariance."""
        toks_a = jnp.asarray([[3, 5, 9, 7]], jnp.int32)
        toks_b = jnp.asarray([[9, 3, 5, 7]], jnp.int32)

        def lm(pos):
            return models.TransformerLM(vocab=61, dim=32, n_layers=1,
                                        n_heads=4, max_seq=64, pos=pos)

        none = lm("none")
        p0 = none.init(jax.random.PRNGKey(0))
        last = lambda m, p, t: np.asarray(m.apply(p, t))[0, -1]
        np.testing.assert_allclose(last(none, p0, toks_a),
                                   last(none, p0, toks_b), atol=1e-5)

        rope = lm("rope")
        p1 = rope.init(jax.random.PRNGKey(0))
        assert not np.allclose(last(rope, p1, toks_a),
                               last(rope, p1, toks_b), atol=1e-4)

    def test_trains(self):
        from distributed_pytorch_tpu import optim
        from distributed_pytorch_tpu.ops.losses import cross_entropy
        from distributed_pytorch_tpu.parallel import make_train_step
        model = self._model()
        params = model.init(jax.random.PRNGKey(0))
        toks = jax.random.randint(jax.random.PRNGKey(1), (2, 17), 0, 61)

        def loss_fn(p, t):
            return cross_entropy(model.apply(p, t[:, :-1]), t[:, 1:]), {}

        opt = optim.adamw(1e-3)
        step = make_train_step(loss_fn, opt, donate=False)
        out = step(params, opt.init(params), toks)
        l0 = float(out.loss.mean())
        for _ in range(5):
            out = step(out.params, out.opt_state, toks)
        assert float(out.loss.mean()) < l0

    def test_cached_decode_matches_full_forward(self):
        """Greedy cached decode (cache holds post-rotation keys; each
        step rotates its slot at the decode position) must equal argmax
        over the full uncached forward."""
        model = self._model()
        params = model.init(jax.random.PRNGKey(0))
        prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 7), 0, 61)
        out = np.asarray(make_generate_fn(model, 6)(
            params, prompt, jax.random.PRNGKey(2)))
        toks = np.asarray(prompt)
        want = []
        for _ in range(6):
            logits = model.apply(params, jnp.asarray(toks))
            nxt = np.asarray(jnp.argmax(logits[:, -1], axis=-1))
            want.append(nxt)
            toks = np.concatenate([toks, nxt[:, None]], axis=1)
        np.testing.assert_array_equal(out, np.stack(want, axis=1))

    def test_rope_gqa_flash_compose(self):
        """RoPE + GQA + flash kernel together match the dense path."""
        from distributed_pytorch_tpu.ops import make_flash_attn_fn
        dense = self._model(n_kv_heads=2)
        flash = self._model(n_kv_heads=2, attn_fn=make_flash_attn_fn(16, 16, min_seq_flash=None))
        params = dense.init(jax.random.PRNGKey(0))
        toks = jax.random.randint(jax.random.PRNGKey(3), (2, 12), 0, 61)
        np.testing.assert_allclose(np.asarray(dense.apply(params, toks)),
                                   np.asarray(flash.apply(params, toks)),
                                   atol=3e-5)

    def test_prefix_consistency(self):
        """A causal prefix run equals the full run restricted to the
        prefix (rope phases are per-position, not per-length)."""
        model = self._model()
        params = model.init(jax.random.PRNGKey(0))
        toks = jax.random.randint(jax.random.PRNGKey(4), (1, 16), 0, 61)
        full = np.asarray(model.apply(params, toks))
        prefix = np.asarray(model.apply(params, toks[:, :8]))
        np.testing.assert_allclose(full[:, :8], prefix, atol=2e-5)

    def test_pos_offset_reaches_rope_phases(self):
        """pos_offset must shift the rope positions handed to every
        block (the sequence-parallel contract: shard r runs with
        pos_offset = r * S_local). A dropped offset is invisible for a
        single contiguous sequence (constant-shift invariance), so this
        checks the plumbing directly: model.apply(pos_offset=7) must
        equal a manual block loop fed positions = 7 + arange(s)."""
        model = self._model()
        params = model.init(jax.random.PRNGKey(0))
        toks = jax.random.randint(jax.random.PRNGKey(5), (1, 8), 0, 61)

        got = np.asarray(model.apply(params, toks, pos_offset=7,
                                     return_hidden=True))

        x = model.tok.apply(params["tok"], toks)
        positions = 7 + jnp.arange(8)
        for blk, p in zip(model.blocks, params["blocks"]):
            x = blk.apply(p, x, positions=positions)
        want = np.asarray(model.ln_f.apply(params["ln_f"], x))
        np.testing.assert_allclose(got, want, atol=1e-6)

        # and offset-0 phases differ from offset-7 phases at the
        # attention level (MHA positions actually matter)
        base = np.asarray(model.apply(params, toks, return_hidden=True))
        x0 = model.tok.apply(params["tok"], toks)
        for blk, p in zip(model.blocks, params["blocks"]):
            x0 = blk.apply(p, x0, positions=jnp.arange(8))
        want0 = np.asarray(model.ln_f.apply(params["ln_f"], x0))
        np.testing.assert_allclose(base, want0, atol=1e-6)
