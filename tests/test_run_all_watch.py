"""The committed recovery automation: benchmarks/run_all_tpu.py's
watch/resume loop. Round-5 lesson encoded as contract: a tunnel that
heals, wedges mid-collection, and heals again must still end with every
stage collected — the old abort-on-wedge path threw a whole round's
evidence away. All backend interaction is mocked; no chip, no
subprocesses."""

import json
import os
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "benchmarks"))

import run_all_tpu  # noqa: E402

from distributed_pytorch_tpu.perfbench import runner  # noqa: E402


def _wire(monkeypatch, tmp_path, *, probe_script, stage_fails,
          watch_healthy=True):
    """Mock the world. probe_script: list of bools consumed by the
    mid-collection health gate (exhausted -> True). stage_fails: dict
    stage name -> number of times it fails before succeeding."""
    calls = {"watch": 0, "probe": 0, "stages": []}
    fails_left = dict(stage_fails)

    monkeypatch.setattr(run_all_tpu, "watch_for_backend",
                        lambda *a, **k: (calls.__setitem__(
                            "watch", calls["watch"] + 1) or watch_healthy))
    # run_all_tpu consumes the probe/wait plumbing from perfbench.runner
    # (bench.py re-exports the same functions for compat)
    monkeypatch.setattr(runner, "wait_for_backend",
                        lambda **k: {"kind": "fake-tpu"})

    def fake_probe(timeout_s=120):
        i = calls["probe"]
        calls["probe"] += 1
        return probe_script[i] if i < len(probe_script) else True

    monkeypatch.setattr(runner, "probe_backend", fake_probe)

    def fake_stage(name, cmd, timeout_s, env=None):
        calls["stages"].append(name)
        if fails_left.get(name, 0) > 0:
            fails_left[name] -= 1
            return {"stage": name, "ok": False,
                    "result": {"error": "mock timeout"}}
        return {"stage": name, "ok": True, "result": {"mock": True}}

    monkeypatch.setattr(run_all_tpu, "run_stage", fake_stage)
    monkeypatch.setattr(run_all_tpu.time, "sleep", lambda s: None)
    monkeypatch.setattr(
        run_all_tpu, "regenerate_baseline",
        lambda *a, **k: calls.__setitem__(
            "regen", calls.get("regen", 0) + 1))
    out = tmp_path / "rows.jsonl"
    return calls, out


def _rows(out):
    return [json.loads(l) for l in out.read_text().splitlines()]


def test_priority_order_smoke_then_flagship():
    """mfu_smoke must be the first stage and the flagship second — the
    first minutes of a heal are the only minutes you are promised."""
    # stage list is built inside _run; assert via a dry parse of the file
    src = open(os.path.join(REPO, "benchmarks", "run_all_tpu.py")).read()
    assert src.index('("mfu_smoke"') < src.index('("bench_mfu"')
    assert src.index('("bench_mfu"') < src.index('("flash_attention"')


def test_watch_resumes_after_midcollection_wedge(monkeypatch, tmp_path):
    # pass 1: smoke ok; flagship fails with the backend HEALTHY (the
    # post-failure probe says so, so the attempt is charged); the gate
    # before the next stage sees a wedge. pass 2 (after re-watch):
    # flagship retried ok, the rest collects.
    calls, out = _wire(monkeypatch, tmp_path,
                       probe_script=[True, True, False],
                       stage_fails={"bench_mfu": 1})
    rc = run_all_tpu._run(["--watch", "--interval", "0",
                           "--max-hours", "1", "--quick",
                           "--out", str(out)])
    assert rc == 0
    assert calls["stages"] == ["mfu_smoke", "bench_mfu",      # pass 1
                               "bench_mfu", "mfu_mid",          # pass 2
                               "flash_attention", "bench_headline"]
    assert calls["watch"] == 2  # initial heal + re-watch after the wedge
    rows = _rows(out)
    gates = [r for r in rows if r["stage"].startswith("health_gate")]
    assert len(gates) == 1 and "pausing queue" in str(gates[0]["result"])
    failed = [r for r in rows if r["stage"] == "bench_mfu" and not r["ok"]]
    assert failed and failed[0]["attempt"] == 1


def test_poison_stage_skipped_after_max_attempts(monkeypatch, tmp_path):
    # flagship fails every time with a healthy backend: after
    # MAX_ATTEMPTS tries it is skipped so the rest still collects.
    calls, out = _wire(monkeypatch, tmp_path, probe_script=[],
                       stage_fails={"bench_mfu": 99})
    rc = run_all_tpu._run(["--watch", "--interval", "0",
                           "--max-hours", "1", "--quick",
                           "--out", str(out)])
    assert rc == 1  # not everything landed — the record must say so
    assert calls["stages"].count("bench_mfu") == run_all_tpu.MAX_ATTEMPTS
    # every other stage succeeded exactly once
    for name in ("mfu_smoke", "mfu_mid", "flash_attention",
                 "bench_headline"):
        assert calls["stages"].count(name) == 1
    attempts = [r["attempt"] for r in _rows(out)
                if r["stage"] == "bench_mfu"]
    assert attempts == [1, 2, 3]


def test_wedge_victim_failures_keep_retry_budget(monkeypatch, tmp_path):
    """A stage whose failures happen with the backend DOWN is a wedge
    victim: the failures must not count against MAX_ATTEMPTS, so the
    stage is still retried on later heals — even past the budget that
    would have skipped a genuine poison stage (ADVICE round 5: the
    flagship was permanently skipped because the tunnel wedged during
    its attempts)."""
    # 4 failures (> MAX_ATTEMPTS), each with the post-failure probe
    # reporting the backend dead; the 5th try succeeds.
    calls, out = _wire(monkeypatch, tmp_path,
                       probe_script=[True, False, False, False, False],
                       stage_fails={"bench_mfu": 4})
    rc = run_all_tpu._run(["--watch", "--interval", "0",
                           "--max-hours", "1", "--quick",
                           "--out", str(out)])
    assert rc == 0
    assert calls["stages"].count("bench_mfu") == 5  # > MAX_ATTEMPTS
    rows = _rows(out)
    failed = [r for r in rows if r["stage"] == "bench_mfu"
              and not r["ok"]]
    assert len(failed) == 4
    assert all(r.get("wedge_victim") for r in failed)
    assert all("attempt" not in r for r in failed)
    # each victim failure pauses the pass (the backend is down — the
    # remaining stages must not burn their timeouts against it)
    gates = [r for r in rows
             if r["stage"].startswith("health_gate_after_bench_mfu")]
    assert len(gates) == 4
    assert any(r["stage"] == "bench_mfu" and r["ok"] for r in rows)


def test_self_wedging_stage_skipped_at_wedge_cap(monkeypatch, tmp_path):
    """The converse guard: a stage that wedges the tunnel ITSELF also
    looks like a wedge victim (the post-failure probe sees the wedge it
    caused), so the exemption is capped — after MAX_WEDGE_VICTIMS such
    failures the stage is skipped and the rest of the queue collects."""
    calls, out = _wire(monkeypatch, tmp_path,
                       probe_script=[True] + [False] * 99,
                       stage_fails={"bench_mfu": 99})
    rc = run_all_tpu._run(["--watch", "--interval", "0",
                           "--max-hours", "1", "--quick",
                           "--out", str(out)])
    assert rc == 1  # bench_mfu never landed — the record says so
    assert calls["stages"].count("bench_mfu") \
        == run_all_tpu.MAX_WEDGE_VICTIMS
    # every other stage still got its shot after the cap
    for name in ("mfu_smoke", "mfu_mid", "flash_attention",
                 "bench_headline"):
        assert calls["stages"].count(name) == 1
    counts = [r["wedge_count"] for r in _rows(out)
              if r["stage"] == "bench_mfu" and not r["ok"]]
    assert counts == list(range(1, run_all_tpu.MAX_WEDGE_VICTIMS + 1))


def test_oneshot_aborts_on_wedge_without_retry(monkeypatch, tmp_path):
    calls, out = _wire(monkeypatch, tmp_path,
                       probe_script=[False],  # wedge right after smoke
                       stage_fails={})
    rc = run_all_tpu._run(["--quick", "--out", str(out)])
    assert rc == 1
    assert calls["stages"] == ["mfu_smoke"]  # flagship never launched
    assert calls["watch"] == 0


def test_full_queue_priority_and_headline_last(monkeypatch, tmp_path):
    """Non-quick: the multi-hour sweep extras splice AFTER the priority
    stages (smoke, flagship, mid bracket, flash) and the composite
    headline stays last — a wedge during the ~3h sweep must not have
    starved the stages added to land early after a heal."""
    calls, out = _wire(monkeypatch, tmp_path, probe_script=[],
                       stage_fails={})
    rc = run_all_tpu._run(["--out", str(out)])
    assert rc == 0
    assert calls["stages"][:5] == ["mfu_smoke", "bench_mfu", "mfu_mid",
                                   "flash_attention", "mfu_sweep"]
    assert calls["stages"][-1] == "bench_headline"


def test_all_ok_single_pass(monkeypatch, tmp_path):
    calls, out = _wire(monkeypatch, tmp_path, probe_script=[],
                       stage_fails={})
    rc = run_all_tpu._run(["--quick", "--out", str(out),
                           "--write-baseline"])
    assert rc == 0
    assert calls["stages"] == ["mfu_smoke", "bench_mfu", "mfu_mid",
                               "flash_attention", "bench_headline"]
    assert all(r["ok"] for r in _rows(out))
    # evidence landed + regen requested -> BASELINE.md regeneration ran
    assert calls.get("regen", 0) == 1


def test_scratch_out_does_not_touch_baseline(monkeypatch, tmp_path):
    """A trial run with a non-default --out must NOT regenerate the
    repo's BASELINE.md measured section from its scratch rows (ADVICE
    round 5); --write-baseline is the explicit override (covered above),
    and the default out path regenerates as before."""
    calls, out = _wire(monkeypatch, tmp_path, probe_script=[],
                       stage_fails={})
    rc = run_all_tpu._run(["--quick", "--out", str(out)])
    assert rc == 0
    assert calls.get("regen", 0) == 0


def test_sweep_arm_error_rows_get_footnote_marker(tmp_path):
    """Arms that exited nonzero after printing a record (arm_error/
    arm_rc) must be visibly annotated in the rendered sweep table, not
    indistinguishable from clean measurements (ADVICE round 5)."""
    from benchmarks import report

    log = tmp_path / "log.jsonl"
    row = {"stage": "mfu_sweep", "ok": True, "ts": "T1", "result": {
        "sweep": [
            {"arm": {"batch": 8}, "mfu": 0.4, "tokens_per_sec": 2.0,
             "step_ms_median": 1.0},
            {"arm": {"batch": 16}, "mfu": 0.5, "tokens_per_sec": 3.0,
             "step_ms_median": 1.0, "arm_error": "rc 1", "arm_rc": 1},
            {"arm": {"batch": 64}, "error": "OOM"},
        ]}}
    log.write_text(json.dumps(row) + "\n")
    md = report.render(report.load_rows(str(log)))
    clean = next(l for l in md.splitlines() if '"batch": 8' in l
                 and l.startswith("|"))
    suspect = next(l for l in md.splitlines() if '"batch": 16' in l
                   and l.startswith("|"))
    assert "†" not in clean
    assert "†" in suspect
    # the footnote explains the marker and carries the rc + error
    assert "exited nonzero after printing its record" in md
    assert "rc 1" in md
    # genuinely failed arms keep their separate failure list
    assert "OOM" in md


def test_retraction_reasons_not_cut_mid_word(tmp_path):
    """Retraction reasons around ~120 chars must render IN FULL (the
    old [:100] cap cut them mid-word — ADVICE round 5); reasons past
    the new cap truncate at a word boundary with an ellipsis."""
    from benchmarks import report

    medium = ("retracted: the measured step time was collected against a "
              "wedged tunnel and understates throughput by roughly 40%")
    assert 100 < len(medium) <= 200
    long = "word " * 60  # 300 chars, > cap
    log = tmp_path / "log.jsonl"
    log.write_text("\n".join(json.dumps(r) for r in [
        {"stage": "bench_mfu", "ok": True, "retracted": True, "ts": "T1",
         "reason": medium},
        {"stage": "mfu_long", "ok": True, "retracted": True, "ts": "T2",
         "reason": long.strip()},
    ]) + "\n")
    md = report.render(report.load_rows(str(log)))
    assert medium in md                      # no truncation at ~120
    cut = next(l for l in md.splitlines() if "mfu_long" in l)
    assert cut.endswith("…")
    body = cut.split("): ", 1)[1][:-1]       # drop the ellipsis
    assert long.startswith(body + " ")       # word-boundary cut


def test_write_baseline_splices_between_markers(tmp_path):
    """report.write_baseline replaces ONLY the marker-delimited span and
    refuses to touch a file whose markers are missing."""
    from benchmarks import report

    doc = tmp_path / "BASELINE.md"
    doc.write_text("intro prose\n" + report.MARK_BEGIN
                   + "\nstale tables\n" + report.MARK_END
                   + "\noutro prose\n")
    assert report.write_baseline("## fresh tables", path=str(doc))
    text = doc.read_text()
    assert "## fresh tables" in text and "stale tables" not in text
    assert text.startswith("intro prose") and "outro prose" in text
    # idempotent: a second write replaces the span again, not nests it
    assert report.write_baseline("## fresher", path=str(doc))
    text2 = doc.read_text()
    assert "## fresher" in text2 and "fresh tables" not in text2
    assert text2.count(report.MARK_BEGIN) == 1

    bare = tmp_path / "no_markers.md"
    bare.write_text("hand-written prose only\n")
    assert not report.write_baseline("## x", path=str(bare))
    assert bare.read_text() == "hand-written prose only\n"
