"""Ring attention (sequence parallelism): exactness vs dense attention,
causal correctness, and the full dp x tp x sp mesh-composed training step."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

import distributed_pytorch_tpu as dist
from distributed_pytorch_tpu import models, optim
from distributed_pytorch_tpu.nn.attention import dense_attention
from distributed_pytorch_tpu.ops.losses import cross_entropy_per_example
from distributed_pytorch_tpu.parallel.sequence import ring_attention
from distributed_pytorch_tpu.parallel.spmd import (make_gspmd_ring_attn_fn,
                                                   make_spmd_train_step,
                                                   shard_batch_spec)
from distributed_pytorch_tpu.parallel.tensor import (
    replicated_specs, shard_params, transformer_lm_param_specs)
from distributed_pytorch_tpu.runtime import context


@pytest.fixture
def sp_mesh8():
    mesh = context.init_mesh(sp=8)
    yield mesh
    dist.cleanup()


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_dense(sp_mesh8, causal):
    """Ring attention over 8 sequence shards == dense attention, exactly."""
    rng = np.random.default_rng(0)
    b, h, s, d = 2, 3, 32, 8
    q = jnp.asarray(rng.standard_normal((b, h, s, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, h, s, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, h, s, d)), jnp.float32)

    want = dense_attention(q, k, v, causal=causal)

    spec = P(None, None, "sp", None)
    f = jax.shard_map(
        lambda q, k, v: ring_attention(q, k, v, axis_name="sp",
                                       causal=causal),
        mesh=sp_mesh8,
        in_specs=(spec, spec, spec), out_specs=spec, check_vma=False)
    got = jax.jit(f)(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-6)


def test_gspmd_ring_attn_island(sp_mesh8):
    """The shard_map island composes inside a jitted GSPMD program."""
    attn = make_gspmd_ring_attn_fn(sp_mesh8)
    rng = np.random.default_rng(1)
    q = jnp.asarray(rng.standard_normal((1, 2, 16, 4)), jnp.float32)
    got = jax.jit(lambda q: attn(q, q, q, causal=True))(q)
    want = dense_attention(q, q, q, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-6)


def _lm_loss(model):
    def loss_fn(p, batch):
        x, y = batch
        logits = model.apply(p, x)
        per_tok = cross_entropy_per_example(logits, y)
        return per_tok.mean(), {}
    return loss_fn


def test_dp_tp_sp_mesh_train_step():
    """Full composition: batch over dp=2, heads/mlp over tp=2, sequence
    over sp=2 — one jitted train step, loss matches the single-device
    run of the same model/batch."""
    mesh = context.init_mesh(dp=2, tp=2, sp=2)
    try:
        model = models.TransformerLM(
            vocab=32, dim=16, n_layers=2, n_heads=2, max_seq=8,
            attn_fn=make_gspmd_ring_attn_fn(mesh))
        ref_model = models.TransformerLM(
            vocab=32, dim=16, n_layers=2, n_heads=2, max_seq=8)

        params0 = ref_model.init(jax.random.PRNGKey(0))
        specs = transformer_lm_param_specs(model)
        params = shard_params(params0, specs, mesh)
        opt = optim.adamw(1e-3)
        opt_state = opt.init(params)

        rng = np.random.default_rng(0)
        toks = rng.integers(0, 32, (4, 8)).astype(np.int32)
        batch = shard_batch_spec((toks, toks), mesh, P("dp", "sp"))

        step = make_spmd_train_step(_lm_loss(model), opt, donate=False)
        out = step(params, opt_state, batch)

        # single-device reference: same params, same batch
        ref_loss, _ = _lm_loss(ref_model)(params0, (jnp.asarray(toks),
                                                    jnp.asarray(toks)))
        np.testing.assert_allclose(float(out.loss), float(ref_loss),
                                   rtol=2e-5)
        # params stay sharded per spec after the update
        qkv_w = out.params["blocks"][0]["attn"]["qkv"]["w"]
        assert qkv_w.sharding.spec == P(None, "tp")

        # and training actually progresses under the full mesh
        losses = [float(out.loss)]
        for _ in range(3):
            out = step(out.params, out.opt_state, batch)
            losses.append(float(out.loss))
        assert losses[-1] < losses[0]
    finally:
        dist.cleanup()


def test_init_mesh_validation():
    with pytest.raises(ValueError):
        context.init_mesh(dp=3, tp=2)  # 6 != 8 devices
