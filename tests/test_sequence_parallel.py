"""Ring attention (sequence parallelism): exactness vs dense attention,
causal correctness, and the full dp x tp x sp mesh-composed training step."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

import distributed_pytorch_tpu as dist
from distributed_pytorch_tpu import models, optim
from distributed_pytorch_tpu.nn.attention import dense_attention
from distributed_pytorch_tpu.ops.losses import cross_entropy_per_example
from distributed_pytorch_tpu.parallel.sequence import ring_attention
from distributed_pytorch_tpu.parallel.spmd import (make_gspmd_ring_attn_fn,
                                                   make_spmd_train_step,
                                                   shard_batch_spec)
from distributed_pytorch_tpu.parallel.tensor import (
    replicated_specs, shard_params, transformer_lm_param_specs)
from distributed_pytorch_tpu.runtime import context
from distributed_pytorch_tpu.runtime.jax_compat import shard_map


@pytest.fixture
def sp_mesh8():
    mesh = context.init_mesh(sp=8)
    yield mesh
    dist.cleanup()


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("h_kv", [4, 2, 1])
def test_ring_attention_matches_dense(sp_mesh8, causal, h_kv):
    """Ring attention over 8 sequence shards == dense attention, exactly
    — including GQA kv heads (h_kv < h) via the grouped block update.
    h_kv=2 is the true grouped case that pins the contiguous
    query-group convention (MQA h_kv=1 cannot — every mapping is
    equivalent there)."""
    rng = np.random.default_rng(0)
    b, h, s, d = 2, 4, 32, 8
    q = jnp.asarray(rng.standard_normal((b, h, s, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, h_kv, s, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, h_kv, s, d)), jnp.float32)

    want = dense_attention(q, k, v, causal=causal)

    spec = P(None, None, "sp", None)
    f = shard_map(
        lambda q, k, v: ring_attention(q, k, v, axis_name="sp",
                                       causal=causal),
        mesh=sp_mesh8,
        in_specs=(spec, spec, spec), out_specs=spec, check_vma=False)
    got = jax.jit(f)(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-6)


def test_gspmd_ring_attn_island(sp_mesh8):
    """The shard_map island composes inside a jitted GSPMD program."""
    attn = make_gspmd_ring_attn_fn(sp_mesh8)
    rng = np.random.default_rng(1)
    q = jnp.asarray(rng.standard_normal((1, 2, 16, 4)), jnp.float32)
    got = jax.jit(lambda q: attn(q, q, q, causal=True))(q)
    want = dense_attention(q, q, q, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-6)


def _lm_loss(model):
    def loss_fn(p, batch):
        x, y = batch
        logits = model.apply(p, x)
        per_tok = cross_entropy_per_example(logits, y)
        return per_tok.mean(), {}
    return loss_fn


@pytest.mark.slow
def test_dp_tp_sp_mesh_train_step():
    """Full composition: batch over dp=2, heads/mlp over tp=2, sequence
    over sp=2 — one jitted train step, loss matches the single-device
    run of the same model/batch."""
    mesh = context.init_mesh(dp=2, tp=2, sp=2)
    try:
        model = models.TransformerLM(
            vocab=32, dim=16, n_layers=2, n_heads=2, max_seq=8,
            attn_fn=make_gspmd_ring_attn_fn(mesh))
        ref_model = models.TransformerLM(
            vocab=32, dim=16, n_layers=2, n_heads=2, max_seq=8)

        params0 = ref_model.init(jax.random.PRNGKey(0))
        specs = transformer_lm_param_specs(model)
        params = shard_params(params0, specs, mesh)
        opt = optim.adamw(1e-3)
        opt_state = opt.init(params)

        rng = np.random.default_rng(0)
        toks = rng.integers(0, 32, (4, 8)).astype(np.int32)
        batch = shard_batch_spec((toks, toks), mesh, P("dp", "sp"))

        step = make_spmd_train_step(_lm_loss(model), opt, donate=False)
        out = step(params, opt_state, batch)

        # single-device reference: same params, same batch
        ref_loss, _ = _lm_loss(ref_model)(params0, (jnp.asarray(toks),
                                                    jnp.asarray(toks)))
        np.testing.assert_allclose(float(out.loss), float(ref_loss),
                                   rtol=2e-5)
        # params stay sharded per spec after the update
        qkv_w = out.params["blocks"][0]["attn"]["qkv"]["w"]
        assert qkv_w.sharding.spec == P(None, "tp")

        # and training actually progresses under the full mesh
        losses = [float(out.loss)]
        for _ in range(3):
            out = step(out.params, out.opt_state, batch)
            losses.append(float(out.loss))
        assert losses[-1] < losses[0]
    finally:
        dist.cleanup()


def test_init_mesh_validation():
    with pytest.raises(ValueError):
        context.init_mesh(dp=3, tp=2)  # 6 != 8 devices


# ---------------------------------------------------------------------------
# ring FLASH attention (pallas core per ring hop)
# ---------------------------------------------------------------------------

from distributed_pytorch_tpu.ops import flash_attention_with_lse  # noqa: E402
from distributed_pytorch_tpu.parallel.sequence import (  # noqa: E402
    ring_flash_attention)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_with_lse_values_and_lse(causal):
    """The lse output equals dense logsumexp of the scaled logits."""
    rng = np.random.default_rng(3)
    b, h, s, d = 2, 2, 32, 8
    q, k, v = (jnp.asarray(rng.standard_normal((b, h, s, d)), jnp.float32)
               for _ in range(3))
    o, lse = flash_attention_with_lse(q, k, v, causal=causal,
                                      block_q=16, block_k=16)
    want_o = dense_attention(q, k, v, causal=causal)
    logits = np.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(d)
    if causal:
        mask = np.tril(np.ones((s, s), bool))
        logits = np.where(mask, logits, -np.inf)
    want_lse = np.log(np.exp(logits - logits.max(-1, keepdims=True))
                      .sum(-1)) + logits.max(-1)
    np.testing.assert_allclose(np.asarray(o), np.asarray(want_o),
                               rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(np.asarray(lse), want_lse,
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_with_lse_grads_include_lse_cotangent(causal):
    """Gradients when the LSE participates in the loss: checks the
    g_lse -> delta adjustment in the backward kernels against autodiff
    through a dense implementation."""
    rng = np.random.default_rng(4)
    b, h, s, d = 1, 2, 24, 8
    q, k, v = (jnp.asarray(rng.standard_normal((b, h, s, d)), jnp.float32)
               for _ in range(3))

    def loss_flash(q, k, v):
        o, lse = flash_attention_with_lse(q, k, v, causal=causal,
                                          block_q=8, block_k=8)
        return jnp.sum(o ** 2) + jnp.sum(jnp.sin(lse))

    def loss_dense(q, k, v):
        logits = (jnp.einsum("bhqd,bhkd->bhqk", q, k)
                  .astype(jnp.float32)) / jnp.sqrt(jnp.float32(d))
        if causal:
            m = jnp.tril(jnp.ones((s, s), bool))
            logits = jnp.where(m, logits, -jnp.inf)
        lse = jax.nn.logsumexp(logits, axis=-1)
        o = jnp.einsum("bhqk,bhkd->bhqd",
                       jnp.exp(logits - lse[..., None]), v)
        return jnp.sum(o ** 2) + jnp.sum(jnp.sin(lse))

    g = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    w = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b_, name in zip(g, w, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=5e-4, atol=5e-4,
                                   err_msg=f"d{name}")


@pytest.mark.parametrize("causal", [False, True])
def test_ring_flash_matches_dense(sp_mesh8, causal):
    """Ring flash attention over 8 sequence shards == dense attention."""
    rng = np.random.default_rng(5)
    b, h, s, d = 2, 2, 64, 8
    q, k, v = (jnp.asarray(rng.standard_normal((b, h, s, d)), jnp.float32)
               for _ in range(3))
    want = dense_attention(q, k, v, causal=causal)
    spec = P(None, None, "sp", None)
    f = shard_map(
        lambda q, k, v: ring_flash_attention(q, k, v, axis_name="sp",
                                             causal=causal, block_q=8,
                                             block_k=8),
        mesh=sp_mesh8, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False)
    got = jax.jit(f)(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.slow
@pytest.mark.parametrize("causal", [False, True])
def test_ring_flash_grads_match_dense(sp_mesh8, causal):
    """jax.grad through the unrolled ring (reverse ppermutes + the flash
    lse backward) == grads of dense attention."""
    rng = np.random.default_rng(6)
    b, h, s, d = 1, 2, 32, 8
    q, k, v = (jnp.asarray(rng.standard_normal((b, h, s, d)), jnp.float32)
               for _ in range(3))
    spec = P(None, None, "sp", None)

    ring = shard_map(
        lambda q, k, v: ring_flash_attention(q, k, v, axis_name="sp",
                                             causal=causal, block_q=4,
                                             block_k=4),
        mesh=sp_mesh8, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False)

    def loss_ring(q, k, v):
        return jnp.sum(ring(q, k, v) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(dense_attention(q, k, v, causal=causal) ** 2)

    g = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    w = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b_, name in zip(g, w, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=5e-4, atol=5e-4,
                                   err_msg=f"d{name}")


def test_dp_tp_sp_tied_embeddings_parity():
    """Tied embeddings under tensor parallelism: the tok table takes the
    vocab sharding (P('tp', None) — the transposed head sharding), and
    the mesh loss matches the single-device run exactly."""
    mesh = context.init_mesh(dp=2, tp=2, sp=2)
    try:
        kw = dict(vocab=32, dim=16, n_layers=2, n_heads=2, max_seq=8,
                  tie_embeddings=True)
        model = models.TransformerLM(
            attn_fn=make_gspmd_ring_attn_fn(mesh), **kw)
        ref_model = models.TransformerLM(**kw)
        params0 = ref_model.init(jax.random.PRNGKey(0))
        assert "head" not in params0
        params = shard_params(params0, transformer_lm_param_specs(model),
                              mesh)
        assert params["tok"]["emb"].sharding.spec == P("tp", None)
        opt = optim.adamw(1e-3)

        toks = np.random.default_rng(0).integers(0, 32, (4, 8)) \
            .astype(np.int32)
        step = make_spmd_train_step(_lm_loss(model), opt, donate=False)
        batch = shard_batch_spec((toks, toks), mesh, P("dp", "sp"))
        out = step(params, opt.init(params), batch)
        ref_loss, _ = _lm_loss(ref_model)(params0, (jnp.asarray(toks),
                                                    jnp.asarray(toks)))
        np.testing.assert_allclose(float(out.loss), float(ref_loss),
                                   rtol=2e-5)
    finally:
        dist.cleanup()


# ---------------------------------------------------------------------------
# striped (load-balanced) causal ring
# ---------------------------------------------------------------------------


def test_stripe_tokens_layout_and_roundtrip():
    """Shard r of the striped layout holds original positions
    {r, r+n, ...} in order; unstripe inverts exactly."""
    from distributed_pytorch_tpu.parallel import (stripe_tokens,
                                                  unstripe_tokens)
    x = jnp.arange(16)
    st = stripe_tokens(x, 4, axis=0)
    np.testing.assert_array_equal(
        np.asarray(st),
        [0, 4, 8, 12, 1, 5, 9, 13, 2, 6, 10, 14, 3, 7, 11, 15])
    np.testing.assert_array_equal(
        np.asarray(unstripe_tokens(st, 4, axis=0)), np.arange(16))
    x2 = jnp.arange(2 * 16 * 3).reshape(2, 16, 3)
    rt = unstripe_tokens(stripe_tokens(x2, 8, axis=1), 8, axis=1)
    np.testing.assert_array_equal(np.asarray(rt), np.asarray(x2))
    with pytest.raises(ValueError):
        stripe_tokens(jnp.arange(10), 4, axis=0)


def test_striped_ring_matches_dense(sp_mesh8):
    """Striped causal ring == dense causal attention on the unstriped
    sequence (every hop a triangular kernel — balance must be layout,
    not math), including GQA kv heads."""
    from distributed_pytorch_tpu.parallel import stripe_tokens, unstripe_tokens
    from distributed_pytorch_tpu.parallel.spmd import (
        make_gspmd_striped_ring_attn_fn)

    rng = np.random.default_rng(1)
    n, (b, h, s, d) = 8, (2, 4, 64, 8)
    q = jnp.asarray(rng.standard_normal((b, h, s, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, h // 2, s, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, h // 2, s, d)), jnp.float32)
    want = dense_attention(q, jnp.repeat(k, 2, 1), jnp.repeat(v, 2, 1),
                           causal=True)

    attn = make_gspmd_striped_ring_attn_fn(sp_mesh8, block_q=4, block_k=4)
    qs, ks, vs = (stripe_tokens(t, n, axis=2) for t in (q, k, v))
    got = unstripe_tokens(
        jax.jit(lambda a, b_, c: attn(a, b_, c, causal=True))(qs, ks, vs),
        n, axis=2)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-4)

    with pytest.raises(ValueError):
        attn(qs, ks, vs, causal=False)  # striped ring is causal-only


def test_striped_ring_grads_match_dense(sp_mesh8):
    from distributed_pytorch_tpu.parallel import stripe_tokens, unstripe_tokens
    from distributed_pytorch_tpu.parallel.spmd import (
        make_gspmd_striped_ring_attn_fn)

    rng = np.random.default_rng(2)
    n, (b, h, s, d) = 8, (1, 2, 32, 8)
    q = jnp.asarray(rng.standard_normal((b, h, s, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, h, s, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, h, s, d)), jnp.float32)
    attn = make_gspmd_striped_ring_attn_fn(sp_mesh8, block_q=4, block_k=4)

    def loss_striped(q, k, v):
        qs, ks, vs = (stripe_tokens(t, n, axis=2) for t in (q, k, v))
        o = unstripe_tokens(attn(qs, ks, vs, causal=True), n, axis=2)
        return jnp.sum(o ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(dense_attention(q, k, v, causal=True) ** 2)

    gs = jax.jit(jax.grad(loss_striped, argnums=(0, 1, 2)))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(gs, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=5e-4, atol=5e-4)


@pytest.mark.slow
def test_striped_lm_training_loss_matches_contiguous():
    """Full LM path in striped layout (tokens+targets+positions striped
    once at the data level, striped ring attention inside) reproduces
    the contiguous dense-attention loss — the data-level contract of
    stripe_tokens."""
    from distributed_pytorch_tpu.parallel import stripe_tokens
    from distributed_pytorch_tpu.parallel.spmd import (
        make_gspmd_striped_ring_attn_fn)

    mesh = context.init_mesh(dp=2, sp=4)
    try:
        n, seq = 4, 32
        kw = dict(vocab=64, dim=32, n_layers=2, n_heads=4, n_kv_heads=2,
                  pos="rope", max_seq=seq)
        m_striped = models.TransformerLM(
            attn_fn=make_gspmd_striped_ring_attn_fn(mesh, block_q=4,
                                                    block_k=4), **kw)
        m_plain = models.TransformerLM(**kw)
        params = m_plain.init(jax.random.PRNGKey(0))
        rng = np.random.default_rng(0)
        toks = rng.integers(0, 64, (4, seq + 1)).astype(np.int32)
        x, y = jnp.asarray(toks[:, :-1]), jnp.asarray(toks[:, 1:])

        oracle = float(cross_entropy_per_example(
            m_plain.apply(params, x), y).mean())

        pos_st = stripe_tokens(jnp.arange(seq), n, axis=0)
        x_st = stripe_tokens(x, n, axis=1)
        y_st = stripe_tokens(y, n, axis=1)
        logits = jax.jit(
            lambda p, t: m_striped.apply(p, t, positions=pos_st))(params,
                                                                  x_st)
        loss = float(cross_entropy_per_example(logits, y_st).mean())
        np.testing.assert_allclose(loss, oracle, rtol=5e-4, atol=5e-4)
    finally:
        dist.cleanup()


# ---------------------------------------------------------------------------
# Ulysses (all-to-all) sequence parallelism
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_matches_dense(sp_mesh8, causal):
    """All-to-all SP == dense attention: heads<->sequence reshard around
    a full-sequence kernel must be pure transport."""
    from distributed_pytorch_tpu.parallel.spmd import make_gspmd_ring_attn_fn

    rng = np.random.default_rng(4)
    b, h, s, d = 2, 8, 64, 16
    q = jnp.asarray(rng.standard_normal((b, h, s, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, h, s, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, h, s, d)), jnp.float32)
    attn = make_gspmd_ring_attn_fn(sp_mesh8, core="ulysses",
                                   block_q=8, block_k=8)
    got = jax.jit(lambda a, b_, c: attn(a, b_, c, causal=causal))(q, k, v)
    want = dense_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-4)

    with pytest.raises(ValueError):  # kv heads must divide the axis
        attn(q, k[:, :4], v[:, :4], causal=causal)


def test_ulysses_gqa_and_grads():
    """GQA (kv heads divisible by sp but < q heads) + gradient parity on
    a 4-shard axis."""
    from distributed_pytorch_tpu.parallel.spmd import make_gspmd_ring_attn_fn

    mesh = context.init_mesh(dp=2, sp=4)
    try:
        rng = np.random.default_rng(5)
        b, h, h_kv, s, d = 2, 8, 4, 32, 8
        q = jnp.asarray(rng.standard_normal((b, h, s, d)), jnp.float32)
        k = jnp.asarray(rng.standard_normal((b, h_kv, s, d)), jnp.float32)
        v = jnp.asarray(rng.standard_normal((b, h_kv, s, d)), jnp.float32)
        attn = make_gspmd_ring_attn_fn(mesh, core="ulysses",
                                       block_q=8, block_k=8)

        def loss_u(q, k, v):
            return jnp.sum(attn(q, k, v, causal=True) ** 2)

        def loss_d(q, k, v):
            return jnp.sum(dense_attention(q, k, v, causal=True) ** 2)

        np.testing.assert_allclose(
            np.asarray(jax.jit(lambda a, b_, c: attn(a, b_, c,
                                                     causal=True))(q, k, v)),
            np.asarray(dense_attention(q, k, v, causal=True)),
            rtol=2e-4, atol=2e-4)
        gu = jax.jit(jax.grad(loss_u, argnums=(0, 1, 2)))(q, k, v)
        gd = jax.grad(loss_d, argnums=(0, 1, 2))(q, k, v)
        for a, b_ in zip(gu, gd):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                       rtol=5e-4, atol=5e-4)
    finally:
        dist.cleanup()


@pytest.mark.slow
def test_striped_moe_lm_matches_contiguous():
    """The striped data-level contract composes with the MoE LM: striped
    tokens/targets/positions + striped ring attention reproduce the
    contiguous dense-attention loss (capacity generous enough that the
    token-choice router drops nothing — drops are layout-order-dependent,
    see stripe_tokens docstring)."""
    from distributed_pytorch_tpu.parallel import stripe_tokens
    from distributed_pytorch_tpu.parallel.spmd import (
        make_gspmd_striped_ring_attn_fn)

    mesh = context.init_mesh(dp=2, sp=4)
    try:
        n, seq = 4, 32
        kw = dict(vocab=64, dim=32, n_layers=2, n_heads=4, n_experts=4,
                  capacity_factor=4.0, pos="rope", max_seq=seq)
        m_striped = models.MoETransformerLM(
            attn_fn=make_gspmd_striped_ring_attn_fn(mesh, block_q=4,
                                                    block_k=4), **kw)
        m_plain = models.MoETransformerLM(**kw)
        params = m_plain.init(jax.random.PRNGKey(0))
        rng = np.random.default_rng(6)
        toks = rng.integers(0, 64, (4, seq + 1)).astype(np.int32)
        x, y = jnp.asarray(toks[:, :-1]), jnp.asarray(toks[:, 1:])

        logits_o, aux_o = m_plain.apply(params, x)
        oracle = float(cross_entropy_per_example(logits_o, y).mean()
                       + 0.01 * aux_o)

        pos_st = stripe_tokens(jnp.arange(seq), n, axis=0)
        x_st = stripe_tokens(x, n, axis=1)
        y_st = stripe_tokens(y, n, axis=1)
        logits, aux = jax.jit(
            lambda p, t: m_striped.apply(p, t, positions=pos_st))(params,
                                                                  x_st)
        loss = float(cross_entropy_per_example(logits, y_st).mean()
                     + 0.01 * aux)
        np.testing.assert_allclose(loss, oracle, rtol=5e-4, atol=5e-4)
    finally:
        dist.cleanup()


# ---------------------------------------------------------------------------
# sliding-window ring attention (banded hops, static far-hop skip)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("window", [4, 12, 200])
@pytest.mark.parametrize("core", ["flash", "ulysses"])
def test_windowed_sp_matches_dense(sp_mesh8, window, core):
    """Sliding-window attention across sequence shards == the dense
    windowed oracle, for windows inside one shard, spanning shards, and
    wider than the whole sequence."""
    rng = np.random.default_rng(7)
    b, h, s, d = 2, 8, 64, 16  # 8 tokens per shard
    q = jnp.asarray(rng.standard_normal((b, h, s, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, h, s, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, h, s, d)), jnp.float32)
    attn = make_gspmd_ring_attn_fn(sp_mesh8, core=core, window=window,
                                   block_q=4, block_k=4)
    got = jax.jit(lambda a, b_, c: attn(a, b_, c, causal=True))(q, k, v)
    want = dense_attention(q, k, v, causal=True, window=window)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=3e-4, atol=3e-4)


def test_windowed_ring_skips_far_hops_statically(sp_mesh8):
    """The O(S*window) claim: with window <= S_local only 2 of the 8
    hops run, so the traced program contains 2 ppermute pairs instead of
    7 — the skip is in the compiled program, not a runtime branch."""
    from distributed_pytorch_tpu.parallel.sequence import (
        ring_flash_attention)
    b, h, s_loc, d = 1, 2, 8, 8

    def island(window):
        spec = P(None, None, "sp", None)
        return shard_map(
            lambda q, k, v: ring_flash_attention(
                q, k, v, axis_name="sp", causal=True, window=window,
                block_q=4, block_k=4),
            mesh=sp_mesh8, in_specs=(spec,) * 3, out_specs=spec,
            check_vma=False)

    x = jnp.zeros((1, 2, 64, 8), jnp.float32)
    narrow = str(jax.make_jaxpr(
        lambda q: island(8)(q, q, q))(x)).count("ppermute")
    full = str(jax.make_jaxpr(
        lambda q: island(None)(q, q, q))(x)).count("ppermute")
    assert narrow < full, (narrow, full)
    assert narrow <= 2 * 2  # hops 0..1 -> at most 2 k/v shift pairs


def test_windowed_ring_grads_match_dense(sp_mesh8):
    rng = np.random.default_rng(8)
    b, h, s, d = 1, 2, 64, 8
    W = 12  # spans shard boundaries
    q = jnp.asarray(rng.standard_normal((b, h, s, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, h, s, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, h, s, d)), jnp.float32)
    attn = make_gspmd_ring_attn_fn(sp_mesh8, core="flash", window=W,
                                   block_q=4, block_k=4)

    def lf(q, k, v):
        return jnp.sum(attn(q, k, v, causal=True) ** 2)

    def ld(q, k, v):
        return jnp.sum(dense_attention(q, k, v, causal=True,
                                       window=W) ** 2)

    gf = jax.jit(jax.grad(lf, argnums=(0, 1, 2)))(q, k, v)
    gd = jax.grad(ld, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(gf, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=5e-4, atol=5e-4)


def test_window_rejected_for_dense_and_striped_cores(sp_mesh8):
    with pytest.raises(ValueError):
        make_gspmd_ring_attn_fn(sp_mesh8, core="dense", window=8)
    with pytest.raises(ValueError):
        make_gspmd_ring_attn_fn(sp_mesh8, core="striped", window=8)
