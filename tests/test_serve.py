"""Continuous-batching inference engine (serve/) — the acceptance suite.

The headline contract: with requests arriving at STAGGERED iterations
(mixed prompt lengths, mixed max-tokens, mid-stream slot retirement +
admission), every request's token sequence is bit-identical to a
standalone ``generate()`` call with the same params/rng, the jitted
decode step compiles exactly once, prefill compiles at most once per
length bucket — and an injected ``DPX_FAULT`` delay surfaces a typed
per-request deadline error without corrupting the other in-flight
requests.
"""

import json
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_pytorch_tpu import models, serve
from distributed_pytorch_tpu.models.generate import (decode_step,
                                                     decode_step_slots,
                                                     make_generate_fn,
                                                     prefill,
                                                     prefill_partial)
from distributed_pytorch_tpu.runtime import faults
from distributed_pytorch_tpu.serve import (AdmissionRejected, EngineConfig,
                                           EngineStopped, InferenceEngine,
                                           RequestDeadlineExceeded,
                                           SamplingParams)
from distributed_pytorch_tpu.utils.logging import MetricsLogger

MAX_LEN = 64


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.reset()
    yield
    faults.reset()


def _lm(**kw):
    kw.setdefault("vocab", 61)
    kw.setdefault("dim", 32)
    kw.setdefault("n_layers", 2)
    kw.setdefault("n_heads", 4)
    kw.setdefault("n_kv_heads", 2)
    kw.setdefault("pos", "rope")
    kw.setdefault("max_seq", 128)
    return models.TransformerLM(**kw)


def _dense_window_fn(w):
    """A sliding-window attention core on the DENSE path (exact same
    function the flash kernel computes — tests/test_flash_attention.py
    proves that equivalence) advertising ``window`` the way
    make_flash_attn_fn does, so _model_window detects it. Used here
    because interpret-mode pallas on CPU is ~10x slower per compile
    and the serving engine only cares about the window ATTRIBUTE."""
    from distributed_pytorch_tpu.nn.attention import dense_attention

    def fn(q, k, v, *, causal=False, scale=None):
        return dense_attention(q, k, v, causal=causal, scale=scale,
                               window=w)
    fn.window = w
    return fn


def _windowed_lm(w=8):
    return _lm(vocab=64, attn_fn=_dense_window_fn(w))


def _lm1(**kw):
    """1-layer variant for engine-BEHAVIOR tests (queue, deadlines,
    shutdown, callbacks): depth adds only compile seconds there —
    the numeric/bit-identity contracts all run on 2-layer models."""
    kw.setdefault("n_layers", 1)
    return _lm(**kw)


def _standalone(model, params, prompt, sp, key, max_len=MAX_LEN):
    """The reference: one-request models.generate with the same
    params/rng (and the same cache width as the engine's slot rows)."""
    fn = make_generate_fn(model, sp.max_new_tokens,
                          temperature=sp.temperature, top_k=sp.top_k,
                          top_p=sp.top_p, max_len=max_len)
    return np.asarray(jax.jit(fn)(params, jnp.asarray(prompt[None]),
                                  key))[0]


# ---------------------------------------------------------------------------
# slot-level cache ops (models/generate.py)
# ---------------------------------------------------------------------------


class TestSlotCacheOps:
    def test_prefill_partial_matches_prefill_bitwise(self):
        """Right-padding is inert under causality: logits at the last
        real position and the cached K/V prefix are bit-identical to an
        exact-length prefill."""
        model = _lm()
        params = model.init(jax.random.PRNGKey(0))
        rng = np.random.default_rng(0)
        prompt = jnp.asarray(rng.integers(0, 61, (1, 7)), jnp.int32)
        logits, cache = jax.jit(
            lambda p, t: prefill(model, p, t, MAX_LEN))(params, prompt)
        padded = jnp.zeros((1, 16), jnp.int32).at[:, :7].set(prompt)
        logits_p, ks, vs = jax.jit(
            lambda p, t, n: prefill_partial(model, p, t, n))(
            params, padded, 7)
        np.testing.assert_array_equal(np.asarray(logits),
                                      np.asarray(logits_p))
        for i in range(model.n_layers):
            np.testing.assert_array_equal(
                np.asarray(cache.k[i])[:, :, :7],
                np.asarray(ks[i])[:, :, :7])
            np.testing.assert_array_equal(
                np.asarray(cache.v[i])[:, :, :7],
                np.asarray(vs[i])[:, :, :7])

    def test_prefill_partial_window_layout(self):
        """The gather-built rolling layout (traced true_len) equals
        prefill's roll-built layout, for prompts shorter AND longer
        than the window (one compile serves both: true_len is traced)."""
        W = 8
        model = _windowed_lm(W)
        params = model.init(jax.random.PRNGKey(0))
        rng = np.random.default_rng(1)
        partial_fn = jax.jit(
            lambda p, t, n: prefill_partial(model, p, t, n, window=W))
        for s in (5, 20):
            prompt = jnp.asarray(rng.integers(0, 64, (1, s)), jnp.int32)
            _, cache = prefill(model, params, prompt, MAX_LEN, window=W)
            padded = jnp.zeros((1, 32), jnp.int32).at[:, :s].set(prompt)
            _, ks, vs = partial_fn(params, padded, s)
            for i in range(model.n_layers):
                np.testing.assert_allclose(np.asarray(cache.k[i]),
                                           np.asarray(ks[i]), atol=1e-6)
                np.testing.assert_allclose(np.asarray(cache.v[i]),
                                           np.asarray(vs[i]), atol=1e-6)

    def test_decode_step_slots_b1_bitwise(self):
        """At the same batch shape the per-row formulation IS
        decode_step: logits and cache writes bit-identical."""
        model = _lm()
        params = model.init(jax.random.PRNGKey(0))
        rng = np.random.default_rng(2)
        prompt = jnp.asarray(rng.integers(0, 61, (1, 9)), jnp.int32)
        logits, cache = jax.jit(
            lambda p, t: prefill(model, p, t, MAX_LEN))(params, prompt)
        tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        ref_l, ref_c = jax.jit(
            lambda p, c, t: decode_step(model, p, c, t))(params, cache, tok)
        got_l, ks, vs = jax.jit(
            lambda p, k, v, ln, t: decode_step_slots(model, p, k, v, ln, t))(
            params, list(cache.k), list(cache.v),
            jnp.asarray([9], jnp.int32), tok)
        np.testing.assert_array_equal(np.asarray(ref_l), np.asarray(got_l))
        for i in range(model.n_layers):
            np.testing.assert_array_equal(np.asarray(ref_c.k[i]),
                                          np.asarray(ks[i]))

    def test_decode_step_slots_row_isolation(self):
        """Changing ANOTHER row's cache/token/length leaves a row's
        logits bitwise unchanged — the slot-independence precondition
        of continuous batching."""
        model = _lm()
        params = model.init(jax.random.PRNGKey(0))
        rng = np.random.default_rng(3)
        prompt = jnp.asarray(rng.integers(0, 61, (1, 6)), jnp.int32)
        _, cache = jax.jit(
            lambda p, t: prefill(model, p, t, MAX_LEN))(params, prompt)
        f = jax.jit(lambda p, k, v, ln, t:
                    decode_step_slots(model, p, k, v, ln, t))

        def pool(rows):          # garbage pool with the real row at 0
            return [jnp.asarray(
                rng.standard_normal((3,) + r.shape[1:]),
                jnp.float32).at[0:1].set(r) for r in rows]

        k_a, v_a = pool(cache.k), pool(cache.v)
        k_b = [c.at[1:].add(1.5) for c in k_a]
        v_b = [c.at[1:].add(-0.5) for c in v_a]
        la = f(params, k_a, v_a, jnp.asarray([6, 3, 11], jnp.int32),
               jnp.asarray([7, 1, 2], jnp.int32))[0]
        lb = f(params, k_b, v_b, jnp.asarray([6, 9, 0], jnp.int32),
               jnp.asarray([7, 5, 60], jnp.int32))[0]
        np.testing.assert_array_equal(np.asarray(la)[0], np.asarray(lb)[0])


# ---------------------------------------------------------------------------
# the engine
# ---------------------------------------------------------------------------


class TestEngine:
    def test_staggered_mix_bit_identical(self):
        """THE acceptance case: staggered arrivals, mixed prompt
        lengths / max-tokens / sampling configs, mid-stream retirement
        + admission — every stream equals standalone generate(), with
        one decode compile and ≤ one prefill compile per bucket."""
        model = _lm()
        params = model.init(jax.random.PRNGKey(0))
        rng = np.random.default_rng(0)
        eng = InferenceEngine(model, params,
                              EngineConfig(n_slots=3, max_len=MAX_LEN))
        # (prompt_len, max_new, sampling): three sampler configs, two
        # prefill buckets, short + long requests
        mix = [
            (5, 30, SamplingParams(max_new_tokens=30)),
            (9, 3, SamplingParams(max_new_tokens=3, temperature=0.7,
                                  top_k=8)),
            (3, 12, SamplingParams(max_new_tokens=12, temperature=0.9,
                                   top_p=0.9)),
            (12, 6, SamplingParams(max_new_tokens=6)),          # queued
            (7, 8, SamplingParams(max_new_tokens=8, temperature=0.7,
                                  top_k=8)),
        ]
        prompts = [rng.integers(0, 61, (s,)).astype(np.int32)
                   for s, _, _ in mix]
        keys = [jax.random.PRNGKey(100 + i) for i in range(len(mix))]
        with eng:
            handles = [eng.submit(prompts[i], mix[i][2], rng=keys[i])
                       for i in range(4)]
            # stagger: the second wave arrives only after an early
            # retirement freed a slot mid-run
            handles[1].result(timeout=60)
            handles += [eng.submit(prompts[i], mix[i][2], rng=keys[i])
                        for i in (4,)]
            outs = [h.result(timeout=60) for h in handles]
        for i, ((s, n, sp), out) in enumerate(zip(mix, outs)):
            ref = _standalone(model, params, prompts[i], sp, keys[i])
            np.testing.assert_array_equal(out, ref, err_msg=f"request {i}")
        st = eng.stats()
        assert st["decode_compiles"] == 1, st
        assert all(v == 1 for v in st["prefill_compiles"].values()), st
        assert st["sample_compiles"] == 3, st
        # continuous batching really happened: request 3 (queued beyond
        # the 3 slots) was admitted only after request 1's mid-stream
        # retirement freed one — while request 0 (30 tokens) was STILL
        # in flight
        admits = [h.metrics["admit_iteration"] for h in handles]
        retires = [h.metrics["retire_iteration"] for h in handles]
        assert admits[3] > retires[1], (admits, retires)  # slot reuse
        assert admits[3] < retires[0], (admits, retires)  # overlap

    def test_windowed_model_rolling_pool(self):
        """Sliding-window model: slot rows are W wide, generation runs
        past the window, streams equal standalone generate()."""
        model = _windowed_lm(8)
        params = model.init(jax.random.PRNGKey(0))
        rng = np.random.default_rng(1)
        eng = InferenceEngine(model, params,
                              EngineConfig(n_slots=2, max_len=32))
        assert eng.pool.width == 8                # O(window) memory
        cases = [(4, 20), (20, 16)]
        with eng:
            hs, refs = [], []
            for i, (s, n) in enumerate(cases):
                prompt = rng.integers(0, 64, (s,)).astype(np.int32)
                key = jax.random.PRNGKey(i)
                sp = SamplingParams(max_new_tokens=n)
                hs.append(eng.submit(prompt, sp, rng=key))
                refs.append(np.asarray(jax.jit(make_generate_fn(model, n))(
                    params, jnp.asarray(prompt[None]), key))[0])
            for h, ref in zip(hs, refs):
                np.testing.assert_array_equal(h.result(timeout=60), ref)
        assert eng.stats()["decode_compiles"] == 1

    def test_eos_truncates_stream(self):
        """eos_token stops the request early (eos included); the
        truncated stream is a prefix of the standalone stream."""
        model = _lm1()
        params = model.init(jax.random.PRNGKey(0))
        prompt = np.arange(5, dtype=np.int32)
        key = jax.random.PRNGKey(42)
        sp = SamplingParams(max_new_tokens=10)
        ref = _standalone(model, params, prompt, sp, key)
        eos = int(ref[4])                         # stop mid-stream
        with InferenceEngine(model, params,
                             EngineConfig(n_slots=1,
                                          max_len=MAX_LEN)) as eng:
            out = eng.submit(prompt,
                             SamplingParams(max_new_tokens=10,
                                            eos_token=eos),
                             rng=key).result(timeout=60)
        k = int(np.argmax(ref == eos)) + 1
        np.testing.assert_array_equal(out, ref[:k])
        assert out[-1] == eos

    def test_bounded_queue_typed_rejection(self):
        model = _lm1()
        params = model.init(jax.random.PRNGKey(0))
        eng = InferenceEngine(model, params,
                              EngineConfig(n_slots=1, max_len=MAX_LEN,
                                           max_queue=2))
        # engine NOT started: the queue only fills
        eng.submit(np.arange(4, dtype=np.int32), SamplingParams())
        eng.submit(np.arange(4, dtype=np.int32), SamplingParams())
        with pytest.raises(AdmissionRejected) as ei:
            eng.submit(np.arange(4, dtype=np.int32), SamplingParams())
        assert ei.value.reason == "queue_full"
        assert ei.value.request_id == 2
        eng.shutdown(wait=False)

    def test_unservable_requests_rejected(self):
        model = _lm1()
        params = model.init(jax.random.PRNGKey(0))
        eng = InferenceEngine(model, params,
                              EngineConfig(n_slots=1, max_len=32))
        with pytest.raises(AdmissionRejected) as ei:
            eng.submit(np.zeros(40, np.int32), SamplingParams())
        assert ei.value.reason == "prompt_too_long"
        with pytest.raises(AdmissionRejected) as ei:
            eng.submit(np.zeros(20, np.int32),
                       SamplingParams(max_new_tokens=20))
        assert ei.value.reason == "too_long"

    def test_priority_over_fcfs(self):
        """With all three queued up front, the priority-0 request is
        admitted first even though it arrived LAST; the two priority-5
        requests then run in arrival order (FCFS within a class)."""
        model = _lm1()
        params = model.init(jax.random.PRNGKey(0))
        eng = InferenceEngine(model, params,
                              EngineConfig(n_slots=1, max_len=MAX_LEN))
        p = np.arange(4, dtype=np.int32)
        ha = eng.submit(p, SamplingParams(max_new_tokens=8, priority=5))
        hb = eng.submit(p, SamplingParams(max_new_tokens=4, priority=5))
        hc = eng.submit(p, SamplingParams(max_new_tokens=4, priority=0))
        with eng:
            for h in (ha, hb, hc):
                h.result(timeout=60)
        assert hc.metrics["admit_iteration"] \
            < ha.metrics["admit_iteration"] \
            < hb.metrics["admit_iteration"]

    def test_queued_deadline_typed_error(self):
        """A request that expires while QUEUED surfaces
        RequestDeadlineExceeded(stage='queued') without occupying a
        slot; the running request is unaffected."""
        model = _lm1()
        params = model.init(jax.random.PRNGKey(0))
        key = jax.random.PRNGKey(5)
        prompt = np.arange(6, dtype=np.int32)
        sp_long = SamplingParams(max_new_tokens=50)
        with InferenceEngine(model, params,
                             EngineConfig(n_slots=1,
                                          max_len=MAX_LEN)) as eng:
            ha = eng.submit(prompt, sp_long, rng=key)
            hb = eng.submit(np.arange(4, dtype=np.int32),
                            SamplingParams(max_new_tokens=4,
                                           deadline_ms=40.0))
            with pytest.raises(RequestDeadlineExceeded) as ei:
                hb.result(timeout=60)
            assert len(ha.result(timeout=60)) == 50  # unaffected
        assert ei.value.stage == "queued"
        assert ei.value.deadline_ms == 40.0
        assert ei.value.request_id == hb.request_id

    def test_chaos_delay_surfaces_running_deadline(self):
        """THE chaos acceptance case: an injected DPX_FAULT delay at a
        known engine iteration stalls the loop past a running request's
        deadline — that request fails TYPED (attributed to request and
        iteration) while the other in-flight request's stream stays
        bit-identical and the engine keeps serving."""
        model = _lm1()
        params = model.init(jax.random.PRNGKey(0))
        rng = np.random.default_rng(7)
        eng = InferenceEngine(model, params,
                              EngineConfig(n_slots=2, max_len=128))
        eng.start()
        try:
            sp_b = SamplingParams(max_new_tokens=20, temperature=0.7,
                                  top_k=8)
            # warm up EVERY compile (bucket-8 prefill, decode, both
            # sampler configs) so post-install iterations are ms-scale:
            # compile time must not eat the deadline
            eng.submit(np.arange(4, dtype=np.int32),
                       SamplingParams(max_new_tokens=2)).result(timeout=60)
            eng.submit(np.arange(4, dtype=np.int32),
                       SamplingParams(max_new_tokens=2, temperature=0.7,
                                      top_k=8)).result(timeout=60)
            # the serve_step op-call counter only advances while specs
            # are installed, so call=3 is the THIRD engine iteration
            # from now — one after the admissions below
            faults.install("delay@op=serve_step,call=3,ms=1200")
            prompt_a = rng.integers(0, 61, (5,)).astype(np.int32)
            prompt_b = rng.integers(0, 61, (8,)).astype(np.int32)
            key_b = jax.random.PRNGKey(9)
            ha = eng.submit(prompt_a,
                            SamplingParams(max_new_tokens=100,
                                           deadline_ms=700.0))
            hb = eng.submit(prompt_b, sp_b, rng=key_b)
            with pytest.raises(RequestDeadlineExceeded) as ei:
                ha.result(timeout=60)
            assert ei.value.stage == "running"
            assert ei.value.request_id == ha.request_id
            assert ei.value.iteration is not None
            assert any(f.startswith("delay@") for f in faults.fired())
            ref_b = _standalone(model, params, prompt_b, sp_b, key_b,
                                max_len=128)
            # the other in-flight request is NOT corrupted
            np.testing.assert_array_equal(hb.result(timeout=60), ref_b)
            # and the engine still serves after the failure
            hc = eng.submit(prompt_b, sp_b, rng=key_b)
            np.testing.assert_array_equal(hc.result(timeout=60), ref_b)
        finally:
            eng.shutdown()

    def test_slo_metrics_flow_to_logger(self, tmp_path):
        """Per-request TTFT/TPOT events and periodic queue-depth /
        slot-occupancy snapshots land in the line-JSON metrics stream —
        the periodic records now ride the ONE dpxmon registry path
        (rank-attributed metrics_snapshot events, obs/metrics.py), and
        every snapshot passes the strict dpxmon validator."""
        from distributed_pytorch_tpu.obs import metrics as dpxmon
        model = _lm1()
        params = model.init(jax.random.PRNGKey(0))
        log = tmp_path / "serve_metrics.jsonl"
        logger = MetricsLogger(path=str(log))
        cfg = EngineConfig(n_slots=2, max_len=MAX_LEN, metrics=logger,
                           log_every=2)
        dpxmon.reset()
        try:
            with InferenceEngine(model, params, cfg) as eng:
                hs = [eng.submit(np.arange(5, dtype=np.int32),
                                 SamplingParams(max_new_tokens=8))
                      for _ in range(3)]
                for h in hs:
                    h.result(timeout=60)
        finally:
            logger.close()
            dpxmon.reset()
        rows = [json.loads(ln) for ln in log.read_text().splitlines()]
        reqs = [r for r in rows if r.get("event") == "serve_request"]
        assert len(reqs) == 3
        for r in reqs:
            assert r["outcome"] == "ok" and r["n_tokens"] == 8
            assert r["ttft_ms"] > 0 and r["tpot_ms"] > 0
            assert r["queue_ms"] is not None
        snaps = [r for r in rows if r.get("event") == "metrics_snapshot"
                 and r.get("source") == "serve_engine"]
        assert snaps, rows
        for r in snaps:
            assert dpxmon.validate_snapshot(r) == []
            m = r["metrics"]
            assert 0.0 <= m["serve.slot_occupancy"] <= 1.0
            assert "serve.queue_depth" in m
        # the SLO histograms feed the health rules: completed requests
        # land TTFT/TPOT summaries in the final snapshots
        last = snaps[-1]["metrics"]
        assert last["serve.completed"] >= 1
        assert last["serve.ttft_ms"]["count"] >= 1

    def test_shutdown_fails_inflight_typed(self):
        model = _lm1()
        params = model.init(jax.random.PRNGKey(0))
        eng = InferenceEngine(model, params,
                              EngineConfig(n_slots=1, max_len=128))
        eng.start()
        h = eng.submit(np.arange(4, dtype=np.int32),
                       SamplingParams(max_new_tokens=100))
        h2 = eng.submit(np.arange(4, dtype=np.int32),
                        SamplingParams(max_new_tokens=4))
        time.sleep(0.05)
        eng.shutdown()
        for handle in (h, h2):
            with pytest.raises(EngineStopped):
                handle.result(timeout=10)

    def test_engine_loop_crash_fails_futures_typed(self):
        """An exception escaping the engine loop must not strand
        futures: every in-flight request fails as EngineStopped with
        the crash chained as the cause."""
        model = _lm1()
        params = model.init(jax.random.PRNGKey(0))
        eng = InferenceEngine(model, params,
                              EngineConfig(n_slots=1, max_len=MAX_LEN))

        def boom(*a, **k):
            raise RuntimeError("injected engine bug")
        eng.pool.admit = boom
        eng.start()
        h = eng.submit(np.arange(4, dtype=np.int32),
                       SamplingParams(max_new_tokens=4))
        with pytest.raises(EngineStopped) as ei:
            h.result(timeout=30)
        assert isinstance(ei.value.__cause__, RuntimeError)
        with pytest.raises(EngineStopped):
            eng.submit(np.arange(4, dtype=np.int32), SamplingParams())
        eng.shutdown()

    def test_streaming_callback_order(self):
        model = _lm1()
        params = model.init(jax.random.PRNGKey(0))
        seen = []
        with InferenceEngine(model, params,
                             EngineConfig(n_slots=1,
                                          max_len=MAX_LEN)) as eng:
            h = eng.submit(np.arange(5, dtype=np.int32),
                           SamplingParams(max_new_tokens=6),
                           on_token=lambda t, i: seen.append((i, t)))
            out = h.result(timeout=60)
        assert [i for i, _ in seen] == list(range(6))
        np.testing.assert_array_equal(np.asarray([t for _, t in seen]),
                                      out)
