"""Disaggregated prefill/decode serving (serve/disagg/) — acceptance.

The headline contracts: (1) exact-handoff (f32) disagg token streams
are BIT-IDENTICAL to standalone ``generate()`` — with exactly ONE
jitted decode program across the whole split and one prefill program
per tail bucket; (2) the q8 handoff stays within an explicit asserted
quality bound (per-element KV error <= scale/2, one-decode-step logit
delta <= 0.05, token divergence <= 25%, first token always exact) at
>= 3.5x fewer handoff bytes than f32, with CommStats booking EQUAL to
the ``wire.handoff_page_wire_bytes`` formula; (3) a prefill engine
killed mid-handoff fails ONLY its in-flight requests — typed
``PrefillEngineDied`` with request + engine attribution — while
co-resident decode streams finish bit-exact.
"""

import json
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_pytorch_tpu import models
from distributed_pytorch_tpu.comm import wire
from distributed_pytorch_tpu.models.generate import make_generate_fn
from distributed_pytorch_tpu.runtime import faults
from distributed_pytorch_tpu.serve import (AdmissionRejected,
                                           DisaggConfig, DisaggEngine,
                                           EngineStopped, HandoffCorrupt,
                                           HandoffTimeout,
                                           PrefillEngineDied,
                                           SamplingParams, aggregate)
from distributed_pytorch_tpu.serve.disagg import (LocalTransport,
                                                  decode_frame,
                                                  encode_frame,
                                                  kv_wire_bytes,
                                                  resolve_handoff_bits)
from distributed_pytorch_tpu.serve.pages import PagedSlotPool
from distributed_pytorch_tpu.serve.types import Request
from distributed_pytorch_tpu.utils.logging import MetricsLogger

MAX_LEN = 64
L = 8   # page_len


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.reset()
    yield
    faults.reset()


def _lm(**kw):
    kw.setdefault("vocab", 61)
    kw.setdefault("dim", 32)
    kw.setdefault("n_layers", 2)
    kw.setdefault("n_heads", 4)
    kw.setdefault("n_kv_heads", 2)
    kw.setdefault("pos", "rope")
    kw.setdefault("max_seq", 128)
    return models.TransformerLM(**kw)


def _lm1(**kw):
    kw.setdefault("n_layers", 1)
    return _lm(**kw)


def _standalone(model, params, prompt, sp, key, max_len=MAX_LEN):
    fn = make_generate_fn(model, sp.max_new_tokens,
                          temperature=sp.temperature, top_k=sp.top_k,
                          top_p=sp.top_p, max_len=max_len)
    return np.asarray(jax.jit(fn)(params, jnp.asarray(prompt[None]),
                                  key))[0]


def _disagg(model, params, **kw):
    kw.setdefault("n_slots", 3)
    kw.setdefault("max_len", MAX_LEN)
    kw.setdefault("page_len", L)
    transport = kw.pop("transport", None)
    return DisaggEngine(model, params, DisaggConfig(**kw),
                        transport=transport)


def _pages(model, params, prompt, bucket=32):
    """Prefill ``prompt`` into a scratch paged pool and extract its
    pages — frame-codec test material with real KV statistics."""
    pool = PagedSlotPool(model, 1, MAX_LEN, page_len=L, n_pages=8,
                         prefix_share=False)
    logits, _, _ = pool.admit(params, prompt, 0, (bucket,))
    length, ks, vs = pool.extract(0)
    return np.asarray(logits)[0], length, ks, vs


# ---------------------------------------------------------------------------
# the frame codec
# ---------------------------------------------------------------------------


class TestHandoffFrames:
    def test_exact_roundtrip_and_accounting(self):
        model = _lm()
        params = model.init(jax.random.PRNGKey(0))
        rng = np.random.default_rng(0)
        prompt = rng.integers(0, 61, (20,)).astype(np.int32)
        logits, length, ks, vs = _pages(model, params, prompt)
        buf, kv_bytes = encode_frame(7, length, logits, ks, vs, None)
        pe = ks[0][0].size
        want = kv_wire_bytes(model.n_layers, len(ks[0]), pe, None)
        assert kv_bytes == want == model.n_layers * 2 * 3 * pe * 4
        assert want == wire.handoff_page_wire_bytes(
            pe, model.n_layers * 2 * 3, bits=None)
        fr = decode_frame(buf)
        assert fr.request_id == 7 and fr.length == length
        assert fr.bits is None and fr.kv_bytes == kv_bytes
        np.testing.assert_array_equal(fr.logits, logits)
        for i in range(model.n_layers):
            np.testing.assert_array_equal(fr.ks[i], ks[i])
            np.testing.assert_array_equal(fr.vs[i], vs[i])

    def test_quant_roundtrip_bound_and_byte_cut(self):
        """The codec quality bound, asserted elementwise: every
        dequantized value is within scale/2 of the original, scale
        local to ITS page (amax/levels) — plus the byte-cut claims the
        CI gates on (q8 >= 3.5x, q4 >= 6.5x under f32)."""
        model = _lm()
        params = model.init(jax.random.PRNGKey(0))
        rng = np.random.default_rng(1)
        prompt = rng.integers(0, 61, (20,)).astype(np.int32)
        logits, length, ks, vs = _pages(model, params, prompt)
        pe = ks[0][0].size
        n_tensors = model.n_layers * 2 * len(ks[0])
        f32_bytes = kv_wire_bytes(model.n_layers, len(ks[0]), pe, None)
        for bits, min_ratio in ((8, 3.5), (4, 6.5)):
            buf, kv_bytes = encode_frame(3, length, logits, ks, vs, bits)
            assert kv_bytes == n_tensors * wire.quant_wire_bytes(
                pe, bits=bits)
            assert f32_bytes / kv_bytes >= min_ratio
            fr = decode_frame(buf)
            np.testing.assert_array_equal(fr.logits, logits)  # always exact
            levels = wire.quant_levels(bits)
            for i in range(model.n_layers):
                for src, got in ((ks[i], fr.ks[i]), (vs[i], fr.vs[i])):
                    for p in range(src.shape[0]):
                        bound = np.abs(src[p]).max() / levels / 2 + 1e-6
                        assert np.abs(src[p] - got[p]).max() <= bound

    def test_corrupt_frames_typed_with_page_attribution(self):
        model = _lm1()
        params = model.init(jax.random.PRNGKey(0))
        prompt = np.arange(12, dtype=np.int32)
        logits, length, ks, vs = _pages(model, params, prompt, bucket=16)
        buf, kv_bytes = encode_frame(5, length, logits, ks, vs, 8)
        # flip a byte in the LAST page tensor's payload
        bad = bytearray(buf)
        bad[-1] ^= 0xFF
        with pytest.raises(HandoffCorrupt) as ei:
            decode_frame(bytes(bad))
        n_tensors = model.n_layers * 2 * len(ks[0])
        assert ei.value.request_id == 5
        assert ei.value.page == n_tensors - 1
        assert ei.value.engine == "prefill"
        # damaged logits attribute as header/logits section (page -1)
        bad = bytearray(buf)
        bad[12 * 8 + 4 * (1 + n_tensors)] ^= 0xFF
        with pytest.raises(HandoffCorrupt) as ei:
            decode_frame(bytes(bad))
        assert ei.value.page == -1 and ei.value.request_id == 5
        # bad magic / truncation are typed too (unattributable)
        with pytest.raises(HandoffCorrupt):
            decode_frame(b"\x00" * len(buf))
        with pytest.raises(HandoffCorrupt):
            decode_frame(buf[:40])
        # damaged GEOMETRY words must be typed HandoffCorrupt as well,
        # never an untyped ValueError/MemoryError that would escape the
        # decode loop's victim-only handling and crash every stream
        for word, value in ((3, 9), (3, -8), (5, 1 << 40), (4, 0),
                            (9, 10_000), (10, -1)):
            bad = bytearray(buf)
            bad[word * 8:(word + 1) * 8] = np.int64(value).tobytes()
            with pytest.raises(HandoffCorrupt):
                decode_frame(bytes(bad))

    def test_width_resolution_and_fault_ops(self):
        assert resolve_handoff_bits("f32") is None
        assert resolve_handoff_bits("q8") == 8
        assert resolve_handoff_bits("q4") == 4
        with pytest.raises(ValueError, match="handoff width"):
            resolve_handoff_bits("q2")
        assert "handoff_send" in faults.COMM_OPS
        assert "handoff_recv" in faults.COMM_OPS
        specs = faults.parse_fault_spec(
            "drop_conn@op=handoff_send,call=2;delay@op=handoff_recv,ms=5")
        assert specs[0].op == "handoff_send"
        assert specs[1].op == "handoff_recv"


# ---------------------------------------------------------------------------
# the split engine
# ---------------------------------------------------------------------------


class TestDisaggEngine:
    def test_exact_streams_bit_identical(self):
        """THE acceptance kernel: cold + shared-prefix + sub-page
        prompts through the split — every stream equals standalone
        generate(), ONE decode program across the split (and ZERO on
        the prefill side), one prefill per tail bucket, prefill-side
        radix reuse accounted, and the handoff bytes booked in
        CommStats equal to the wire formula exactly."""
        model = _lm1()
        params = model.init(jax.random.PRNGKey(0))
        rng = np.random.default_rng(12)
        eng = _disagg(model, params)
        pfx = rng.integers(0, 61, (16,)).astype(np.int32)  # 2 full pages
        prompts = [
            np.concatenate([pfx, rng.integers(0, 61, (4,))]).astype(np.int32),
            np.concatenate([pfx, rng.integers(0, 61, (4,))]).astype(np.int32),
            rng.integers(0, 61, (7,)).astype(np.int32),
        ]
        sp = SamplingParams(max_new_tokens=8)
        keys = [jax.random.PRNGKey(100 + i) for i in range(3)]
        with eng:
            hs = [eng.submit(prompts[i], sp, rng=keys[i])
                  for i in range(3)]
            outs = [h.result(timeout=120) for h in hs]
        for i in range(3):
            np.testing.assert_array_equal(
                outs[i], _standalone(model, params, prompts[i], sp,
                                     keys[i]), err_msg=f"request {i}")
        st = eng.stats()
        assert st["decode"]["decode_compiles"] == 1, st
        assert st["prefill"]["decode_compiles"] == 0, st
        assert all(v == 1
                   for v in st["prefill"]["prefill_compiles"].values())
        assert st["decode"]["prefill_compiles"] == {}
        # prefill-side radix reuse: request 1 shares both prefix pages
        assert [h.metrics["prefix_hit_pages"] for h in hs] == [0, 2, 0]
        assert [h.metrics["prefill_tokens_saved"] for h in hs] == [0, 16, 0]
        # byte accounting: CommStats == sum of per-request formula bytes
        pe = model.n_kv_heads * L * (model.dim // model.n_heads)
        want = sum(kv_wire_bytes(model.n_layers, -(-len(p) // L), pe,
                                 None) for p in prompts)
        assert st["handoff"]["bytes_sent"] == want
        assert st["handoff"]["bytes_recv"] == want
        assert want == sum(h.metrics["handoff_bytes"] for h in hs)
        assert st["handoff"]["frames_sent"] == 3
        # all pages released on both sides
        assert eng.decode.pool.pool.live_pages() == 0
        assert eng.prefill.pool.pool.live_pages() == 0

    def test_q8_handoff_quality_bound(self):
        """The q8 contract, asserted: >= 3.5x fewer handoff bytes than
        f32 (CommStats == formula), first token EXACT (logits ship
        f32), and token divergence vs generate() <= 25% — measured 0%
        for this model/population; the bound leaves margin, it does
        not hide drift."""
        model = _lm1()
        params = model.init(jax.random.PRNGKey(0))
        rng = np.random.default_rng(3)
        prompts = [rng.integers(0, 61, (s,)).astype(np.int32)
                   for s in (20, 12, 7, 17)]
        sp = SamplingParams(max_new_tokens=10)
        keys = [jax.random.PRNGKey(200 + i) for i in range(len(prompts))]
        refs = [_standalone(model, params, p, sp, k)
                for p, k in zip(prompts, keys)]
        eng = _disagg(model, params, handoff_width="q8")
        with eng:
            hs = [eng.submit(prompts[i], sp, rng=keys[i])
                  for i in range(len(prompts))]
            outs = [h.result(timeout=120) for h in hs]
        st = eng.stats()
        pe = model.n_kv_heads * L * (model.dim // model.n_heads)
        q8_want = sum(kv_wire_bytes(model.n_layers, -(-len(p) // L),
                                    pe, 8) for p in prompts)
        f32_want = sum(kv_wire_bytes(model.n_layers, -(-len(p) // L),
                                     pe, None) for p in prompts)
        assert st["handoff"]["bytes_sent"] == q8_want
        assert f32_want / q8_want >= 3.5
        divergence = [float(np.mean(o != r))
                      for o, r in zip(outs, refs)]
        for i, (o, r) in enumerate(zip(outs, refs)):
            assert o[0] == r[0], f"request {i}: first token must be exact"
        assert max(divergence) <= 0.25, divergence

    def test_q8_one_step_logit_delta_bound(self):
        """Unit-level quality bound: the same extracted pages adopted
        exact vs through the q8 frame, one decode step — max logit
        delta <= 0.05 (measured ~3.5e-3 here; the bound is explicit
        and asserted, not folklore)."""
        model = _lm()
        params = model.init(jax.random.PRNGKey(0))
        rng = np.random.default_rng(4)
        prompt = rng.integers(0, 61, (20,)).astype(np.int32)
        logits, length, ks, vs = _pages(model, params, prompt)
        out = {}
        for bits in (None, 8):
            fr = decode_frame(encode_frame(1, length, logits, ks, vs,
                                           bits)[0])
            pool = PagedSlotPool(model, 1, MAX_LEN, page_len=L,
                                 n_pages=8, prefix_share=False)
            pool.adopt(0, fr.length, fr.ks, fr.vs)
            lg = pool.decode(params, np.asarray([prompt[-1]], np.int32),
                             np.asarray([True]))
            out[bits] = np.asarray(lg)[0]
        assert np.abs(out[8] - out[None]).max() <= 0.05

    def test_chaos_prefill_death_mid_handoff_victim_only(self):
        """THE chaos satellite: the transport severed entering request
        1's handoff (the in-process analog of killing the prefill
        engine mid-handoff). The victim AND the still-queued request
        fail typed PrefillEngineDied with request + blamed-engine
        attribution, new submissions are refused with reason
        prefill_dead, and the co-resident DECODING stream finishes
        bit-identical to generate()."""
        model = _lm1()
        params = model.init(jax.random.PRNGKey(0))
        rng = np.random.default_rng(8)
        faults.install("drop_conn@op=handoff_send,call=2")
        eng = _disagg(model, params, n_slots=2)
        a = rng.integers(0, 61, (9,)).astype(np.int32)
        b = rng.integers(0, 61, (12,)).astype(np.int32)
        ka, kb = jax.random.PRNGKey(1), jax.random.PRNGKey(2)
        sp = SamplingParams(max_new_tokens=20)
        with eng:
            ha = eng.submit(a, sp, rng=ka)
            while not ha.tokens:   # a must be decoding before b's handoff
                time.sleep(0.005)
            hb = eng.submit(b, sp, rng=kb)
            with pytest.raises(PrefillEngineDied) as ei:
                hb.result(timeout=60)
            out_a = ha.result(timeout=60)
            with pytest.raises(AdmissionRejected) as rej:
                eng.submit(a, sp, rng=ka)
        assert ei.value.request_id == hb.request_id
        assert ei.value.engine == "prefill"
        assert rej.value.reason == "prefill_dead"
        np.testing.assert_array_equal(
            out_a, _standalone(model, params, a, sp, ka))
        assert any(f.startswith("drop_conn@op=handoff_send")
                   for f in faults.fired()), faults.fired()
        assert eng.decode.pool.pool.live_pages() == 0

    def test_handoff_timeout_typed(self):
        """A frame that never materializes (send stalled past
        DPX_HANDOFF_TIMEOUT_MS by an injected delay) fails its request
        as a typed HandoffTimeout with the deadline attributed; the
        co-resident stream is untouched."""
        model = _lm1()
        params = model.init(jax.random.PRNGKey(0))
        rng = np.random.default_rng(9)
        faults.install("delay@op=handoff_send,call=2,ms=600")
        eng = _disagg(model, params, n_slots=2, handoff_timeout_ms=80)
        a = rng.integers(0, 61, (9,)).astype(np.int32)
        b = rng.integers(0, 61, (6,)).astype(np.int32)
        ka, kb = jax.random.PRNGKey(1), jax.random.PRNGKey(2)
        sp = SamplingParams(max_new_tokens=24)
        with eng:
            ha = eng.submit(a, sp, rng=ka)
            while not ha.tokens:
                time.sleep(0.005)
            hb = eng.submit(b, sp, rng=kb)
            with pytest.raises(HandoffTimeout) as ei:
                hb.result(timeout=60)
            out_a = ha.result(timeout=60)
        assert ei.value.request_id == hb.request_id
        assert ei.value.deadline_ms == 80.0
        assert ei.value.engine == "transport"
        np.testing.assert_array_equal(
            out_a, _standalone(model, params, a, sp, ka))

    def test_corrupt_frame_fails_victim_only(self):
        """A frame damaged in flight fails ITS request typed
        (HandoffCorrupt, page-attributed) — the co-resident stream
        decodes on bit-exact and later handoffs flow normally."""
        model = _lm1()
        params = model.init(jax.random.PRNGKey(0))
        rng = np.random.default_rng(10)

        class Flipper(LocalTransport):
            def send(self, frame, kv_bytes):
                if self.frames_sent == 1:     # damage the 2nd frame
                    frame = bytearray(frame)
                    frame[-1] ^= 0xFF
                    frame = bytes(frame)
                super().send(frame, kv_bytes)

        eng = _disagg(model, params, n_slots=2, transport=Flipper())
        a = rng.integers(0, 61, (9,)).astype(np.int32)
        b = rng.integers(0, 61, (6,)).astype(np.int32)
        c = rng.integers(0, 61, (11,)).astype(np.int32)
        ka, kb, kc = (jax.random.PRNGKey(i) for i in (1, 2, 3))
        sp = SamplingParams(max_new_tokens=16)
        with eng:
            ha = eng.submit(a, sp, rng=ka)
            while not ha.tokens:
                time.sleep(0.005)
            hb = eng.submit(b, sp, rng=kb)
            with pytest.raises(HandoffCorrupt) as ei:
                hb.result(timeout=60)
            hc = eng.submit(c, sp, rng=kc)
            out_c = hc.result(timeout=60)
            out_a = ha.result(timeout=60)
        assert ei.value.request_id == hb.request_id
        assert ei.value.page >= 0
        np.testing.assert_array_equal(
            out_a, _standalone(model, params, a, sp, ka))
        np.testing.assert_array_equal(
            out_c, _standalone(model, params, c, sp, kc))

    def test_submit_validation_typed(self):
        model = _lm1()
        params = model.init(jax.random.PRNGKey(0))
        eng = _disagg(model, params, n_slots=1)
        with pytest.raises(AdmissionRejected) as ei:
            eng.submit(np.arange(80, dtype=np.int32),
                       SamplingParams(max_new_tokens=4))
        assert ei.value.reason == "prompt_too_long"
        with pytest.raises(AdmissionRejected) as ei:
            eng.submit(np.arange(40, dtype=np.int32),
                       SamplingParams(max_new_tokens=40))
        assert ei.value.reason == "too_long"
        small = _disagg(model, params, n_slots=1, max_len=32, n_pages=2)
        with pytest.raises(AdmissionRejected) as ei:
            small.submit(np.arange(10, dtype=np.int32),
                         SamplingParams(max_new_tokens=10))
        assert ei.value.reason == "no_free_pages"

    def test_shutdown_drains_typed(self):
        model = _lm1()
        params = model.init(jax.random.PRNGKey(0))
        eng = _disagg(model, params)
        h = eng.submit(np.arange(6, dtype=np.int32),
                       SamplingParams(max_new_tokens=4))
        eng.shutdown()           # never started: queued request drains
        with pytest.raises(EngineStopped) as ei:
            h.result(timeout=10)
        assert ei.value.request_id == h.request_id

    def test_nonpollable_transport_rejected(self):
        """A transport whose recv can only block (the cross-process
        HostCommTransport shape) would stall the decode loop's token
        cadence on the handoff channel — refused at construction."""
        model = _lm1()
        params = model.init(jax.random.PRNGKey(0))

        class Blocking(LocalTransport):
            pollable = False

        with pytest.raises(ValueError, match="not pollable"):
            _disagg(model, params, transport=Blocking())

    def test_windowed_model_rejected(self):
        from distributed_pytorch_tpu.nn.attention import dense_attention

        def fn(q, k, v, *, causal=False, scale=None):
            return dense_attention(q, k, v, causal=causal, scale=scale,
                                   window=8)
        fn.window = 8
        model = _lm1(vocab=64, attn_fn=fn)
        params = model.init(jax.random.PRNGKey(0))
        with pytest.raises(ValueError, match="sliding-window"):
            _disagg(model, params)


# ---------------------------------------------------------------------------
# metrics: the TTFT decomposition and decode-only TPOT attribution
# ---------------------------------------------------------------------------


class TestHandoffMetrics:
    def _req(self, rid, t0=100.0, queue=0.010, prefill=0.020,
             handoff=0.005, decode=0.003, n_tokens=4, tpot=0.002,
             nbytes=1000):
        r = Request(request_id=rid, prompt=np.arange(9, dtype=np.int32),
                    params=SamplingParams(max_new_tokens=n_tokens),
                    rngs=None, submit_t=t0, deadline_t=None)
        r.admit_t = t0 + queue
        r.handoff_send_t = r.admit_t + prefill
        r.handoff_recv_t = r.handoff_send_t + handoff
        r.first_token_t = r.handoff_recv_t + decode
        r.last_token_t = r.first_token_t + tpot * (n_tokens - 1)
        r.out_tokens = list(range(n_tokens))
        r.handoff_bytes = nbytes
        return r

    def test_record_decomposition_sums_to_ttft(self):
        from distributed_pytorch_tpu.serve import request_record
        rec = request_record(self._req(1), "ok")
        assert rec["queue_ms"] == pytest.approx(10.0)
        assert rec["prefill_ms"] == pytest.approx(20.0)
        assert rec["handoff_ms"] == pytest.approx(5.0)
        assert rec["decode_ms"] == pytest.approx(3.0)
        assert rec["handoff_bytes"] == 1000
        assert (rec["queue_ms"] + rec["prefill_ms"] + rec["handoff_ms"]
                + rec["decode_ms"]) == pytest.approx(rec["ttft_ms"])
        # TPOT spans decode-engine time ONLY: first->last token, both
        # emitted by the decode loop — a 100x longer prefill leaves it
        # untouched
        assert rec["tpot_ms"] == pytest.approx(2.0)
        slow = request_record(self._req(2, prefill=2.0), "ok")
        assert slow["tpot_ms"] == pytest.approx(2.0)
        assert slow["prefill_ms"] == pytest.approx(2000.0)

    def test_aggregate_handoff_fleet_view(self):
        from distributed_pytorch_tpu.serve import request_record
        recs = [request_record(self._req(i, handoff=0.004 + 0.002 * i,
                                         nbytes=500 * (i + 1)), "ok")
                for i in range(5)]
        agg = aggregate(recs)
        assert agg["handoff_ms_p50"] == pytest.approx(8.0)
        assert agg["handoff_ms_p99"] == pytest.approx(12.0)
        assert agg["handoff_bytes"] == 500 * (1 + 2 + 3 + 4 + 5)
        assert agg["prefill_ms_p50"] == pytest.approx(20.0)
        # monolithic records have no handoff timeline -> no fleet keys
        mono = dict(recs[0])
        for k in ("prefill_ms", "handoff_ms", "decode_ms",
                  "handoff_bytes"):
            mono.pop(k)
        agg2 = aggregate([mono])
        assert "handoff_ms_p50" not in agg2

    def test_engine_metrics_flow_to_logger(self, tmp_path):
        """Live engine: serve_request events carry the decomposition +
        handoff bytes; every span is nonnegative and the timeline is
        ordered (handoff_recv precedes the first token — TPOT is
        decode-attributable by construction)."""
        model = _lm1()
        params = model.init(jax.random.PRNGKey(0))
        rng = np.random.default_rng(11)
        log = tmp_path / "disagg.jsonl"
        logger = MetricsLogger(path=str(log))
        eng = _disagg(model, params, metrics=logger, log_every=2)
        with eng:
            hs = [eng.submit(rng.integers(0, 61, (9,)).astype(np.int32),
                             SamplingParams(max_new_tokens=6),
                             rng=jax.random.PRNGKey(i))
                  for i in range(3)]
            for h in hs:
                h.result(timeout=120)
        logger.close()
        rows = [json.loads(ln) for ln in log.read_text().splitlines()]
        reqs = [r for r in rows if r.get("event") == "serve_request"]
        assert len(reqs) == 3
        for r in reqs:
            for k in ("queue_ms", "prefill_ms", "handoff_ms",
                      "decode_ms"):
                assert r[k] is not None and r[k] >= 0, (k, r)
            assert r["handoff_bytes"] > 0
            assert (r["queue_ms"] + r["prefill_ms"] + r["handoff_ms"]
                    + r["decode_ms"]) == pytest.approx(r["ttft_ms"],
                                                       rel=1e-6)
        for h in hs:
            req = h._request
            assert req.handoff_recv_t <= req.first_token_t
        agg = aggregate([h.metrics for h in hs])
        assert agg["handoff_bytes"] == sum(
            h.metrics["handoff_bytes"] for h in hs)
        assert agg["handoff_ms_p50"] is not None


# ---------------------------------------------------------------------------
# cross-process transport (separate prefill/decode OS processes)
# ---------------------------------------------------------------------------


def _xproc_worker(rank, world, q):
    """Rank 0 = prefill side, rank 1 = decode side, over the native
    host group. Rank 0 sends one good frame then is hard-KILLED by the
    DPX_FAULT grammar entering its second send; rank 1 round-trips the
    first frame and observes the death as a typed, attributed failure
    within the comm deadline."""
    import numpy as np
    import distributed_pytorch_tpu as dist
    from distributed_pytorch_tpu.runtime import context
    from distributed_pytorch_tpu.serve.disagg import (HostCommTransport,
                                                      decode_frame,
                                                      encode_frame)
    from distributed_pytorch_tpu.serve.disagg.transport import \
        TransportSevered

    dist.init_process_group(rank, world)
    try:
        comm = context.get_host_comm()
        t = HostCommTransport(comm, src=0)
        rng = np.random.default_rng(0)
        ks = [rng.standard_normal((2, 2, 4, 4)).astype(np.float32)]
        vs = [rng.standard_normal((2, 2, 4, 4)).astype(np.float32)]
        logits = rng.standard_normal((16,)).astype(np.float32)
        if rank == 0:
            frame, kv = encode_frame(9, 7, logits, ks, vs, 8)
            t.send(frame, kv)
            # the 2nd send never happens: kill@op=handoff_send,call=2
            # fires in the hook — a real mid-handoff process death
            t.send(frame, kv)
            q.put((rank, "unreachable"))
        else:
            fr = decode_frame(t.recv())
            ok = (fr.request_id == 9 and fr.length == 7
                  and np.array_equal(fr.logits, logits))
            try:
                t.recv()
                q.put((rank, "no-error"))
            except TransportSevered as e:
                q.put((rank, ("severed", ok,
                              type(e.__cause__).__name__)))
    finally:
        dist.cleanup()


def test_hostcomm_transport_kill_prefill_process():
    """The cross-process leg: frames move between REAL OS processes
    over HostComm, and a prefill process hard-killed mid-handoff
    (kill@op=handoff_send — exit 43, indistinguishable from OOM)
    surfaces on the decode side as a typed severed transport blamed on
    a dead peer, within one comm deadline."""
    import multiprocessing as mp

    from distributed_pytorch_tpu.runtime.multiprocess import \
        launch_multiprocess

    faults.install("kill@op=handoff_send,call=2,rank=0")
    q = mp.get_context("spawn").Queue()
    with pytest.raises(RuntimeError):
        # rank 0's injected death propagates as the launcher's typed
        # child-failure report (exit code 43)
        launch_multiprocess(_xproc_worker, 2, q)
    got = {}
    while not q.empty():
        rank, payload = q.get()
        got[rank] = payload
    assert 0 not in got          # rank 0 died before reporting
    kind, first_ok, cause = got[1]
    assert kind == "severed" and first_ok
    assert cause in ("CommPeerDied", "CommTimeout")
