"""Multi-replica fleet (serve/fleet/) — the acceptance suite.

The headline contracts: a fleet of R replicas serves a shared-prefix
mix with every stream bit-identical to a standalone ``generate()``
call (routing never changes tokens); capacity back-pressure spills
typed and attributed, and a fully-exhausted fleet rejects
synchronously with ``reason="fleet_exhausted"``; draining finishes
in-flight streams bit-exact and re-homes the prefix shard; killing a
replica fails ONLY its in-flight requests as replica-attributed
``ReplicaFailed`` (double-resolve safe) while the fleet HealthMonitor
verdict runs degraded → recovered with rule+replica attribution.
"""

import json
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_pytorch_tpu import models
from distributed_pytorch_tpu.models.generate import make_generate_fn
from distributed_pytorch_tpu.obs import export as dpxexport
from distributed_pytorch_tpu.obs import health as dpxhealth
from distributed_pytorch_tpu.obs import metrics as dpxmon
from distributed_pytorch_tpu.runtime import faults
from distributed_pytorch_tpu.serve import (AdmissionRejected, EngineConfig,
                                           SamplingParams)
from distributed_pytorch_tpu.serve.fleet import (REPLICA_RETIRED,
                                                 AutoscaleConfig,
                                                 FleetAutoscaler,
                                                 FleetConfig, FleetRouter,
                                                 ReplicaFailed, placement)
from distributed_pytorch_tpu.utils.logging import MetricsLogger

MAX_LEN = 64


@pytest.fixture(autouse=True)
def _clean(monkeypatch):
    faults.reset()
    dpxmon.reset()
    yield
    faults.reset()
    dpxmon.reset()


def _lm(**kw):
    kw.setdefault("vocab", 61)
    kw.setdefault("dim", 32)
    kw.setdefault("n_layers", 1)
    kw.setdefault("n_heads", 4)
    kw.setdefault("n_kv_heads", 2)
    kw.setdefault("pos", "rope")
    kw.setdefault("max_seq", 128)
    return models.TransformerLM(**kw)


def _standalone(model, params, prompt, sp, key, max_len=MAX_LEN):
    fn = make_generate_fn(model, sp.max_new_tokens,
                          temperature=sp.temperature, top_k=sp.top_k,
                          top_p=sp.top_p, max_len=max_len)
    return np.asarray(jax.jit(fn)(params, jnp.asarray(
        np.asarray(prompt, np.int32)[None]), key))[0]


def _events(path, name):
    out = []
    with open(path) as f:
        for line in f:
            rec = json.loads(line)
            if rec.get("event") == name:
                out.append(rec)
    return out


# ---------------------------------------------------------------------------
# placement (pure, no engines)
# ---------------------------------------------------------------------------


class TestPlacement:
    def test_prefix_key_is_first_full_page(self):
        toks = np.arange(40)
        assert placement.prefix_key(toks, 16) \
            == np.asarray(toks[:16], np.int32).tobytes()
        short = np.arange(5)
        assert placement.prefix_key(short, 16) \
            == np.asarray(short, np.int32).tobytes()

    def test_rendezvous_minimal_disruption(self):
        """HRW's operational property: removing one replica re-homes
        ONLY the keys that homed there — every other key's placement
        (and its warm prefix pages) is untouched."""
        keys = [placement.prefix_key(np.arange(16) + i, 16)
                for i in range(64)]
        before = {k: placement.rendezvous(k, [0, 1, 2]) for k in keys}
        assert len(set(before.values())) > 1   # spread over replicas
        after = {k: placement.rendezvous(k, [0, 2]) for k in keys}
        for k in keys:
            if before[k] != 1:
                assert after[k] == before[k]
            else:
                assert after[k] in (0, 2)

    def test_spill_order_prefers_home_until_backpressure(self):
        key = b"k"
        loads = {0: (0, 0.0), 1: (2, 0.0)}
        assert placement.spill_order(key, 0, loads, 4)[0] == 0
        # home at/past the spill threshold with a lighter peer: proactive
        assert placement.spill_order(key, 1, {0: (0, 0.0), 1: (4, 0.0)},
                                     4)[0] == 0
        # every peer just as loaded: stay home
        assert placement.spill_order(key, 1, {0: (4, 0.0), 1: (4, 0.0)},
                                     4)[0] == 1


# ---------------------------------------------------------------------------
# routing: bit-exactness, affinity, spill, exhaustion
# ---------------------------------------------------------------------------


class TestFleetRouting:
    def test_shared_prefix_mix_bit_exact_with_affinity(self, tmp_path):
        """R=2 paged fleet over a shared-prefix mix: every stream is
        bit-identical to standalone generate() with the fleet rng key,
        regardless of which replica served it; affinity hit rate > 0;
        every route is a logged, attributed fleet_route event."""
        model = _lm()
        params = model.init(jax.random.PRNGKey(0))
        log = str(tmp_path / "fleet.jsonl")
        cfg = FleetConfig(
            n_replicas=2, metrics=MetricsLogger(log),
            engine=EngineConfig(n_slots=2, max_len=MAX_LEN, paged=True))
        fleet = FleetRouter(model, params, cfg)
        sp = SamplingParams(max_new_tokens=8)
        prefix = np.arange(16) % 61
        prompts = [np.concatenate([prefix, [i + 1, i + 2]])
                   for i in range(4)]
        prompts += [(np.arange(18) + 7 * i) % 61 for i in range(3)]
        with fleet:
            handles = [fleet.submit(p, sp) for p in prompts]
            outs = [h.result(timeout=120) for h in handles]
            st = fleet.stats()
        assert st["completed"] == len(prompts)
        assert st["route_affinity_hit_rate"] > 0
        for p, h, out in zip(prompts, handles, outs):
            ref = _standalone(model, params, p, sp,
                              jax.random.PRNGKey(h.request_id))
            assert np.array_equal(out, ref)
        routes = _events(log, "fleet_route")
        assert len(routes) == len(prompts)
        assert all({"request_id", "replica", "home", "spilled"}
                   <= set(r) for r in routes)
        served = {r["replica"] for r in routes}
        assert served <= {0, 1}

    def test_spill_then_fleet_exhausted_typed(self, tmp_path):
        """Deterministic back-pressure (engines never started, so
        queues only fill): the home replica's queue_full rejection
        spills — typed, from/to-attributed — and once EVERY replica is
        full the next submit fails synchronously with
        reason="fleet_exhausted" and the last rejection chained."""
        model = _lm()
        params = model.init(jax.random.PRNGKey(0))
        log = str(tmp_path / "fleet.jsonl")
        cfg = FleetConfig(
            n_replicas=2, metrics=MetricsLogger(log), spill_queue=99,
            engine=EngineConfig(n_slots=1, max_len=MAX_LEN, max_queue=2))
        fleet = FleetRouter(model, params, cfg)   # NOT started
        sp = SamplingParams(max_new_tokens=4)
        prompt = np.arange(12) % 61
        handles = [fleet.submit(prompt, sp) for _ in range(4)]
        with pytest.raises(AdmissionRejected) as ei:
            fleet.submit(prompt, sp)
        assert ei.value.reason == "fleet_exhausted"
        assert ei.value.request_id == 4
        assert isinstance(ei.value.__cause__, AdmissionRejected)
        assert ei.value.__cause__.reason == "queue_full"
        spills = _events(log, "fleet_spill")
        assert len(spills) == 2   # requests 2,3 overflowed to the peer
        home = fleet.home_of(prompt)
        assert all(s["from_replica"] == home
                   and s["to_replica"] != home for s in spills)
        assert fleet.stats()["spills"] == 2
        # the queued work is real: start the fleet and finish it all
        with fleet:
            outs = [h.result(timeout=120) for h in handles]
        assert all(len(o) == 4 for o in outs)

    def test_deterministic_rejection_does_not_walk(self):
        """A prompt every replica must reject identically (too long)
        surfaces as its own typed reason, not fleet_exhausted."""
        model = _lm()
        params = model.init(jax.random.PRNGKey(0))
        fleet = FleetRouter(model, params, FleetConfig(
            n_replicas=2, engine=EngineConfig(n_slots=1,
                                              max_len=MAX_LEN)))
        with pytest.raises(AdmissionRejected) as ei:
            fleet.submit(np.arange(MAX_LEN) % 61,
                         SamplingParams(max_new_tokens=8))
        assert ei.value.reason == "too_long"


# ---------------------------------------------------------------------------
# drain: finish in-flight, re-home the shard
# ---------------------------------------------------------------------------


class TestDrain:
    def test_drain_while_streaming_bit_exact_and_rehomes(self, tmp_path):
        model = _lm()
        params = model.init(jax.random.PRNGKey(0))
        log = str(tmp_path / "fleet.jsonl")
        fleet = FleetRouter(model, params, FleetConfig(
            n_replicas=2, metrics=MetricsLogger(log),
            engine=EngineConfig(n_slots=2, max_len=MAX_LEN)))
        sp = SamplingParams(max_new_tokens=24)
        prompt = np.arange(14) % 61
        with fleet:
            victim = fleet.home_of(prompt)
            h = fleet.submit(prompt, sp)
            while not h.tokens:           # mid-stream, provably
                time.sleep(0.005)
            assert fleet.drain_replica(victim, rule="sustained_ok")
            # never killed mid-stream: the stream finished, bit-exact
            out = h.result(timeout=120)
            ref = _standalone(model, params, prompt, sp,
                              jax.random.PRNGKey(h.request_id))
            assert np.array_equal(out, ref)
            assert fleet.stats()["replicas"][victim]["state"] \
                == REPLICA_RETIRED
            # prefix re-homing: the same prompt now homes elsewhere,
            # and serving still works
            new_home = fleet.home_of(prompt)
            assert new_home is not None and new_home != victim
            h2 = fleet.submit(prompt, sp)
            assert h2.replica == new_home
            assert np.array_equal(
                h2.result(timeout=120),
                _standalone(model, params, prompt, sp,
                            jax.random.PRNGKey(h2.request_id)))
        drained = _events(log, "replica_drained")
        assert len(drained) == 1 and drained[0]["rank"] == victim
        assert any(r["action"] == "drain" and r["replica"] == victim
                   for r in _events(log, "fleet_scale"))

    def test_drain_last_live_replica_refused(self):
        model = _lm()
        params = model.init(jax.random.PRNGKey(0))
        fleet = FleetRouter(model, params, FleetConfig(
            n_replicas=1, engine=EngineConfig(n_slots=1,
                                              max_len=MAX_LEN)))
        with pytest.raises(ValueError, match="last live"):
            fleet.drain_replica(0)


# ---------------------------------------------------------------------------
# failure isolation: the fleet-kill headline
# ---------------------------------------------------------------------------


class TestReplicaFailure:
    def test_kill_isolates_and_health_recovers(self, tmp_path):
        """Killing one replica fails ONLY its in-flight requests —
        typed ReplicaFailed, replica + request attributed, double-
        resolve safe — while co-resident streams on the survivor
        complete bit-exact, the shard re-homes, and the fleet
        HealthMonitor (fed the fleet's own event log) runs
        degraded → recovered keyed on the victim replica."""
        model = _lm()
        params = model.init(jax.random.PRNGKey(0))
        log = str(tmp_path / "fleet.jsonl")
        fleet = FleetRouter(model, params, FleetConfig(
            n_replicas=2, metrics=MetricsLogger(log),
            engine=EngineConfig(n_slots=2, max_len=MAX_LEN)))
        sp = SamplingParams(max_new_tokens=48)
        pa = np.arange(14) % 61
        with fleet:
            victim = fleet.home_of(pa)
            pb = pa
            for s in range(1, 400):       # a prompt homed elsewhere
                pb = (np.arange(14) + s) % 61
                if fleet.home_of(pb) != victim:
                    break
            ha = fleet.submit(pa, sp)
            hb = fleet.submit(pb, sp)
            while not ha.tokens:
                time.sleep(0.005)
            fleet.kill_replica(victim)
            with pytest.raises(ReplicaFailed) as ei:
                ha.result(timeout=60)
            assert ei.value.replica == victim
            assert ei.value.request_id == ha.request_id
            assert ei.value.__cause__ is not None
            # double-resolve gate across the failover: same typed
            # failure again, never a second resolution
            with pytest.raises(ReplicaFailed):
                ha.result(timeout=1)
            # only the victim's requests failed: the co-resident
            # stream completes bit-exact
            out_b = hb.result(timeout=120)
            assert np.array_equal(
                out_b, _standalone(model, params, pb, sp,
                                   jax.random.PRNGKey(hb.request_id)))
            # the victim's shard re-homed over the survivor
            assert fleet.home_of(pa) != victim
            # relaunch under the SAME id (elastic discipline), then a
            # fleet snapshot names it live again
            fleet.revive_replica(victim)
            h3 = fleet.submit(pa, sp)
            assert np.array_equal(
                h3.result(timeout=120),
                _standalone(model, params, pa, sp,
                            jax.random.PRNGKey(h3.request_id)))
            fleet.emit_snapshot()
            fleet.emit_snapshot()
        failed = _events(log, "replica_failed")
        assert len(failed) == 1 and failed[0]["rank"] == victim
        # the fleet's own log drives the monitor degraded → recovered
        # with replica attribution
        records, bad = dpxexport.read_log(log)
        assert not bad
        mon = dpxhealth.scan_records(
            records, dpxhealth.HealthMonitor(
                dpxhealth.parse_rules("fleet.max_queue_depth<=9999")))
        trs = [(t["from"], t["to"], t["rule"], t["rank"])
               for t in mon.transitions]
        assert ("ok", "degraded", dpxhealth.FAILURE_RULE, victim) in trs
        assert mon.state == dpxhealth.OK
        assert trs[-1][1] == dpxhealth.OK

    def test_fleet_log_is_valid_vocabulary(self, tmp_path):
        """Every fleet event passes the strict dpxtrace vocabulary
        check (KNOWN_EVENTS registration + rank-attributed failures)."""
        model = _lm()
        params = model.init(jax.random.PRNGKey(0))
        log = str(tmp_path / "fleet.jsonl")
        fleet = FleetRouter(model, params, FleetConfig(
            n_replicas=2, metrics=MetricsLogger(log),
            engine=EngineConfig(n_slots=1, max_len=MAX_LEN)))
        with fleet:
            h = fleet.submit(np.arange(10) % 61,
                             SamplingParams(max_new_tokens=4))
            h.result(timeout=120)
            fleet.kill_replica(1 - h.replica, reason="test")
            fleet.emit_snapshot()
        issues = dpxexport.check_log(*dpxexport.read_log(log))
        assert issues == [], issues


# ---------------------------------------------------------------------------
# SLO-driven elasticity
# ---------------------------------------------------------------------------


class TestAutoscaler:
    def test_add_on_degraded_drain_on_sustained_ok(self, tmp_path):
        """A TTFT-p99 breach adds a replica (rule-attributed); a
        sustained-ok streak drains the youngest back down — the whole
        loop driven through injected snapshots, engines never started
        (the policy is what's under test, not the engines)."""
        model = _lm()
        params = model.init(jax.random.PRNGKey(0))
        log = str(tmp_path / "fleet.jsonl")
        fleet = FleetRouter(model, params, FleetConfig(
            n_replicas=1, metrics=MetricsLogger(log),
            engine=EngineConfig(n_slots=1, max_len=MAX_LEN)))
        scaler = FleetAutoscaler(fleet, AutoscaleConfig(
            min_replicas=1, max_replicas=2,
            rules="serve.ttft_ms.p99<=500", drain_after_ok=3))
        bad = {"serve.ttft_ms": {"p99": 4000.0}}
        good = {"serve.ttft_ms": {"p99": 20.0}}
        d = scaler.step(bad)
        assert d == {"action": "add", "replica": 1,
                     "rule": "serve.ttft_ms.p99<=500",
                     "state": dpxhealth.DEGRADED}
        assert len(fleet._admitting()) == 2
        assert scaler.step(bad) is None     # already at max
        drains = []
        for _ in range(8):
            d = scaler.step(good)
            if d:
                drains.append(d)
        assert drains == [{"action": "drain", "replica": 1,
                           "rule": "sustained_ok",
                           "state": dpxhealth.OK}]
        assert len(fleet._admitting()) == 1
        scale = _events(log, "fleet_scale")
        assert [r["action"] for r in scale] == ["add", "drain"]
        assert all("rule" in r and "replica" in r for r in scale)
