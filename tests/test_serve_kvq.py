"""Quantized-at-rest paged KV pool (``serve/pages/`` ``kv_dtype``).

What must hold (ISSUE 16 / docs/serving.md "Quantized resident pool"):

- the jnp in-program page codec and the numpy host/wire codec are
  BIT-identical — one block grid (``comm/wire.py``'s QUANT_BLOCK over
  the flat page) shared by pool, kernel and handoff frame;
- the quality contract: per-element KV error <= scale/2 (every element
  quantized exactly once, from exact f32, on page completion), cold
  first tokens exact, one-step logit deltas bounded, bounded token
  divergence on a mixed cold/shared stream;
- the exact default: ``kv_dtype="f32"`` is bit-identical to the
  pre-existing pool — zero behavior change unless opted in;
- the ONE-decode-program discipline survives quantization;
- ``extract``/``adopt`` work at all three widths (stale tails zeroed,
  sub-page tails exact), and the matched-width handoff pass-through
  (``extract_quantized``/``encode_frame_quantized``/``decode_frame(
  keep_bits)``/``adopt_quantized``) moves the pool's resident bits
  byte-identically with no dequant→requant double hop;
- ``PagedSlotPool.admit`` rejects a tail longer than every bucket as a
  typed ``AdmissionRejected(reason="tail_too_long")`` BEFORE any state
  change (regression: this used to escape as a bare StopIteration with
  pages already refcounted).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from distributed_pytorch_tpu import models
from distributed_pytorch_tpu.comm import wire
from distributed_pytorch_tpu.ops.quant import (dequantize_page_blocks,
                                               pack_page_nibbles,
                                               page_block_map,
                                               quantize_page_blocks,
                                               unpack_page_nibbles)
from distributed_pytorch_tpu.serve import (EngineConfig, InferenceEngine,
                                           SamplingParams)
from distributed_pytorch_tpu.serve.disagg import frames
from distributed_pytorch_tpu.serve.pages import PagedSlotPool
from distributed_pytorch_tpu.serve.pages.quant import (dequantize_page_np,
                                                       pack_pages_np,
                                                       quantize_page_np,
                                                       resolve_kv_bits,
                                                       unpack_pages_np)
from distributed_pytorch_tpu.serve.types import AdmissionRejected

MAX_LEN = 64
L = 8
BUCKETS = (8, 16, 32)


def _lm(**kw):
    kw.setdefault("vocab", 61)
    kw.setdefault("dim", 32)
    kw.setdefault("n_layers", 2)
    kw.setdefault("n_heads", 4)
    kw.setdefault("n_kv_heads", 2)
    kw.setdefault("pos", "rope")
    kw.setdefault("max_seq", 128)
    return models.TransformerLM(**kw)


def _pool(model, kv_dtype, **kw):
    kw.setdefault("n_slots", 2)
    kw.setdefault("page_len", L)
    kw.setdefault("n_pages", 32)
    return PagedSlotPool(model, kw.pop("n_slots"), MAX_LEN,
                         kv_dtype=kv_dtype, **kw)


def _greedy_run(model, params, pool, prompt, steps):
    """Admit + ``steps`` greedy decodes on slot 0; returns (tokens,
    first logits, per-step logits)."""
    logits, _, _ = pool.admit(params, prompt, 0, BUCKETS)
    first = np.asarray(logits)[0].copy()
    toks = [int(np.argmax(first))]
    active = np.zeros(pool.n_slots, bool)
    active[0] = True
    cur = np.zeros(pool.n_slots, np.int32)
    step_logits = []
    for _ in range(steps):
        pool.ensure_decode_capacity(0)
        cur[0] = toks[-1]
        lg = np.asarray(pool.decode(params, cur, active))[0].copy()
        step_logits.append(lg)
        toks.append(int(np.argmax(lg)))
    return toks, first, step_logits


# ---------------------------------------------------------------------------
# the one block codec: jnp in-program face == numpy host/wire face
# ---------------------------------------------------------------------------


class TestPageCodec:
    @pytest.mark.parametrize("bits", [8, 4])
    def test_jnp_codec_bit_identical_to_wire(self, bits):
        """``quantize_page_blocks`` (traced, page-shaped, zero-padded
        to the block grid) must agree BIT-for-bit with
        ``wire.quantize_blocks`` on the unpadded flat page — the
        property that makes the matched-width handoff pass-through
        byte-identical."""
        rng = np.random.default_rng(0)
        # (Hkv, L, Dh) pages: generic, zero-block, and integer-snap
        pages = [rng.standard_normal((4, 8, 34)).astype(np.float32),
                 np.zeros((4, 8, 34), np.float32),
                 rng.integers(-5, 6, (4, 8, 34)).astype(np.float32)]
        for page in pages:
            qj, sj = quantize_page_blocks(jnp.asarray(page), bits)
            qn, sn = wire.quantize_blocks(page.ravel(), bits=bits)
            nb = wire.num_blocks(page.size)
            assert np.array_equal(np.asarray(qj).ravel(), qn)
            assert np.array_equal(np.asarray(sj), sn[:nb])
            # and both dequant faces agree with each other
            bmap = page_block_map(4, 8, 34)
            dj = np.asarray(dequantize_page_blocks(qj, sj, bmap))
            dn = wire.dequantize_blocks(qn, sn).reshape(page.shape)
            assert np.array_equal(dj, dn)

    def test_nibble_pack_both_faces_byte_identical(self):
        rng = np.random.default_rng(1)
        q = rng.integers(-7, 8, (4, 8, 34)).astype(np.int8)
        pj = np.asarray(pack_page_nibbles(jnp.asarray(q)))
        pn = pack_pages_np(q)
        assert np.array_equal(pj, pn)
        assert np.array_equal(pn.ravel(),
                              wire.pack_nibbles(q.ravel()))
        uj = np.asarray(unpack_page_nibbles(jnp.asarray(pn)))
        un = unpack_pages_np(pn)
        assert np.array_equal(uj, q) and np.array_equal(un, q)

    @pytest.mark.parametrize("bits", [8, 4])
    def test_per_element_error_bound_half_scale(self, bits):
        """The contract the deferred-tail design buys: every resident
        element is within scale/2 of its exact value (one rounding,
        from exact f32 — never re-rounded)."""
        rng = np.random.default_rng(2)
        page = rng.standard_normal((2, 8, 16)).astype(np.float32) * 3.0
        q, scales = quantize_page_np(page, bits)
        deq = dequantize_page_np(q, scales)
        per_elem_scale = scales[
            np.arange(page.size) // wire.QUANT_BLOCK].reshape(page.shape)
        assert np.all(np.abs(deq - page) <= per_elem_scale / 2 + 1e-7)

    def test_resolve_kv_bits(self):
        assert resolve_kv_bits("f32") is None
        assert resolve_kv_bits("q8") == 8
        assert resolve_kv_bits("q4") == 4
        with pytest.raises(ValueError, match="kv_dtype"):
            resolve_kv_bits("int8")

    def test_q4_odd_head_dim_rejected(self):
        model = _lm(dim=36, n_heads=4, n_kv_heads=2)   # Dh = 9, odd
        with pytest.raises(ValueError, match="even"):
            _pool(model, "q4")


# ---------------------------------------------------------------------------
# quality contract vs the exact pool
# ---------------------------------------------------------------------------


class TestQuantPoolQuality:
    def test_f32_mode_bit_identical_and_q8_bounded(self):
        """One admit + greedy decode run per width. ``f32`` must be
        bit-identical to the default pool (zero behavior change);
        ``q8`` must keep first logits EXACT (cold prefill attends
        in-register f32), one-step logit deltas under the ceiling, and
        the ONE-decode-program discipline."""
        model = _lm()
        params = model.init(jax.random.PRNGKey(0))
        prompt = np.random.default_rng(0).integers(
            0, 61, 21).astype(np.int32)
        base = _greedy_run(model, params, _pool(model, "f32"), prompt, 12)
        ref = _greedy_run(model, params,
                          PagedSlotPool(model, 2, MAX_LEN, page_len=L,
                                        n_pages=32), prompt, 12)
        assert base[0] == ref[0]
        assert np.array_equal(base[1], ref[1])
        for a, b in zip(base[2], ref[2]):
            assert np.array_equal(a, b)
        for kv_dtype in ("q8", "q4"):
            pool = _pool(model, kv_dtype)
            toks, first, steps = _greedy_run(model, params, pool,
                                             prompt, 12)
            # cold admission: the whole prompt is computed in-register
            # (no quantized prefix pages to read) — token 0 exact
            assert np.array_equal(first, base[1]), kv_dtype
            assert pool.compiles.decode == 1, kv_dtype
            if kv_dtype == "q8":
                # one-step logit delta ceiling on the smoke model
                deltas = [float(np.abs(a - b).max())
                          for a, b in zip(steps, base[2])]
                assert max(deltas) <= 0.05, deltas
                div = np.mean([a != b for a, b in zip(toks, base[0])])
                assert div <= 0.25, (toks, base[0])

    def test_engine_q8_mixed_stream_quality(self):
        """Engine-level mixed cold/shared population: q8 vs f32 token
        divergence bounded, cold first tokens exact, decode stays one
        program, and the capacity gauges tell the ~4x story."""
        model = _lm()
        params = model.init(jax.random.PRNGKey(0))
        rng = np.random.default_rng(1)
        shared = rng.integers(0, 61, 16).astype(np.int32)
        prompts = [np.concatenate(
            [shared, rng.integers(0, 61, 5 + i).astype(np.int32)])
            for i in range(3)] + [rng.integers(0, 61, 11).astype(np.int32)]
        sp = SamplingParams(max_new_tokens=8, temperature=0.0)

        def run(kv_dtype):
            eng = InferenceEngine(model, params, EngineConfig(
                paged=True, n_slots=3, max_len=MAX_LEN, page_len=L,
                kv_dtype=kv_dtype))
            with eng:
                hs = [eng.submit(p, sp) for p in prompts]
                outs = [h.result(timeout=120) for h in hs]
            return outs, eng.stats()

        o_f, st_f = run("f32")
        o_q, st_q = run("q8")
        assert st_q["decode_compiles"] == 1
        assert o_q[0][0] == o_f[0][0]          # cold request, token 0
        assert o_q[3][0] == o_f[3][0]          # fully cold prompt
        div = np.mean([a != b for x, y in zip(o_f, o_q)
                       for a, b in zip(x, y)])
        assert div <= 0.25
        pf, pq = st_f["pages"], st_q["pages"]
        assert pq["kv_dtype"] == "q8" and pq["kv_bits"] == 8
        assert pf["kv_bits"] == 32
        ratio = (pf["bytes_per_resident_token"]
                 / pq["bytes_per_resident_token"])
        assert ratio >= 3.5

    @pytest.mark.parametrize("s", [13, 16])   # sub-page tail / aligned
    def test_resident_kv_error_within_half_scale(self, s):
        """Pool-level per-element bound: on a cold prefill (where the
        hidden states feeding the pool are exact — offset-0 admission
        computes everything in-register, never reading quantized
        prefix), the quantized pool's extracted KV is within scale/2 of
        the exact pool's, elementwise — the quantize-once discipline
        measured end-to-end. Decode-written positions are deliberately
        excluded: once attention reads quantized history the hidden
        states themselves drift, so the per-element bound vs an f32
        pool only holds for prefill-covered positions (the end-to-end
        decode quality is gated by the logit/token ceilings above)."""
        model = _lm()
        params = model.init(jax.random.PRNGKey(0))
        prompt = np.random.default_rng(3).integers(
            0, 61, s).astype(np.int32)
        pf = _pool(model, "f32")
        pq = _pool(model, "q8")
        pf.admit(params, prompt, 0, BUCKETS)
        pq.admit(params, prompt, 0, BUCKETS)
        length, ksf, vsf = pf.extract(0)
        length_q, ksq, vsq = pq.extract(0)
        assert length == length_q == s
        for i in range(model.n_layers):
            for exact, got, scales in (
                    (ksf[i], ksq[i], np.asarray(pq.k_scales[i])),
                    (vsf[i], vsq[i], np.asarray(pq.v_scales[i]))):
                row = pq.owned[0]
                per_page = scales[np.asarray(row)]      # (P, nb)
                bound = per_page[
                    :, np.arange(exact[0].size) // wire.QUANT_BLOCK
                ].reshape(exact.shape) / 2
                assert np.all(np.abs(got - exact) <= bound + 1e-6)
                # and the bound is tight enough to matter: the last
                # page's scales are ones only when it never completed
                assert np.any(np.abs(got - exact) > 0)


# ---------------------------------------------------------------------------
# extract / adopt / handoff pass-through
# ---------------------------------------------------------------------------


class TestExtractAdopt:
    @pytest.mark.parametrize("kv_dtype", ["f32", "q8", "q4"])
    def test_extract_zeroes_stale_tail(self, kv_dtype):
        """A released slot's buffers keep the old occupant's values; a
        re-admission with a SHORTER sub-page tail must not ship them:
        positions past ``length`` in the extracted last page are
        zeroed at every width."""
        model = _lm()
        params = model.init(jax.random.PRNGKey(0))
        rng = np.random.default_rng(4)
        pool = _pool(model, kv_dtype, prefix_share=False)
        pool.admit(params, rng.integers(0, 61, 15).astype(np.int32),
                   0, BUCKETS)
        pool.release(0)
        # shorter prompt: 11 = one full page + 3-token tail; stale
        # positions 3..7 of the old occupant's tail must extract as 0
        pool.admit(params, rng.integers(0, 61, 11).astype(np.int32),
                   0, BUCKETS)
        length, ks, vs = pool.extract(0)
        assert length == 11
        for arr in ks + vs:
            assert arr.shape[0] == 2
            assert np.all(arr[-1, :, 3:, :] == 0.0)
            assert np.any(arr[-1, :, :3, :] != 0.0)

    @pytest.mark.parametrize("kv_dtype", ["f32", "q8", "q4"])
    @pytest.mark.parametrize("s", [11, 16])   # sub-page tail / aligned
    def test_adopt_round_trip(self, kv_dtype, s):
        """extract → adopt into a second pool → extract again must be
        value-stable at every width, and the adopted slot must keep
        decoding. f32 is bit-identical. For quantized pools the requant
        of already-dequantized pages reproduces the same q codes, but
        the scale pays a double rounding (``fl(fl(levels·s)/levels)``
        can land one ulp off ``s``), so the extracted values agree to
        one ulp of the scale, not bit-for-bit."""
        model = _lm()
        params = model.init(jax.random.PRNGKey(0))
        prompt = np.random.default_rng(5).integers(
            0, 61, s).astype(np.int32)
        src = _pool(model, kv_dtype, prefix_share=False)
        dst = _pool(model, kv_dtype, prefix_share=False)
        logits, _, _ = src.admit(params, prompt, 0, BUCKETS)
        length, ks, vs = src.extract(0)
        dst.adopt(1, length, ks, vs)
        length2, ks2, vs2 = dst.extract(1)
        assert length2 == length
        for a, b in zip(ks + vs, ks2 + vs2):
            if kv_dtype == "f32":
                assert np.array_equal(a, b)
            else:
                # same q everywhere, scale within one ulp → relative
                # error bounded by one f32 ulp; exact zeros stay zeros
                assert np.allclose(a, b, rtol=2.5e-7, atol=0.0)
                assert np.array_equal(a == 0.0, b == 0.0)
        # the adopted stream decodes: logits must match the source
        # pool's next step exactly (same resident values in both pools)
        tok = int(np.argmax(np.asarray(logits)[0]))
        for pool, slot in ((src, 0), (dst, 1)):
            pool.ensure_decode_capacity(slot)
        active_s = np.zeros(2, bool)
        active_s[0] = True
        active_d = np.zeros(2, bool)
        active_d[1] = True
        cur_s = np.zeros(2, np.int32)
        cur_d = np.zeros(2, np.int32)
        cur_s[0] = tok
        cur_d[1] = tok
        lg_s = np.asarray(src.decode(params, cur_s, active_s))[0]
        lg_d = np.asarray(dst.decode(params, cur_d, active_d))[1]
        if kv_dtype == "f32":
            assert np.array_equal(lg_s, lg_d)
        else:
            # the sub-page tail pays ONE extra rounding at the handoff
            # boundary (exact f32 → quantized frame → dequantized
            # tail); full pages are bit-identical
            assert np.abs(lg_s - lg_d).max() <= 0.05

    @pytest.mark.parametrize("kv_dtype", ["q8", "q4"])
    @pytest.mark.parametrize("s", [11, 16])
    def test_matched_width_passthrough_bit_identical(self, kv_dtype, s):
        """The no-double-hop contract: a quantized pool's resident bits
        cross the frame VERBATIM when pool and wire widths match — and
        the frame carries the same q codes the dequant→requant trip it
        replaces would produce (one shared block codec; the requant
        scale can sit one ulp off the resident scale — double rounding
        — which is exactly the drift the pass-through eliminates)."""
        model = _lm()
        params = model.init(jax.random.PRNGKey(0))
        bits = resolve_kv_bits(kv_dtype)
        prompt = np.random.default_rng(6).integers(
            0, 61, s).astype(np.int32)
        src = _pool(model, kv_dtype, prefix_share=False)
        logits, _, _ = src.admit(params, prompt, 0, BUCKETS)
        lg = np.asarray(logits)[0]
        length, kqs, vqs = src.extract_quantized(0)
        frame_q, nq = frames.encode_frame_quantized(
            7, length, lg, kqs, vqs, bits)
        # same layout and q codes as requantizing the dequantized
        # extraction; scales agree to one ulp
        _, ks, vs = src.extract(0)
        frame_f, nf = frames.encode_frame(7, length, lg, ks, vs, bits)
        assert nq == nf and len(frame_q) == len(frame_f)
        fr_rq = frames.decode_frame(frame_f, keep_bits=bits)
        fr_pt = frames.decode_frame(frame_q, keep_bits=bits)
        for (qa, sa), (qb, sb) in zip(fr_pt.ks + fr_pt.vs,
                                      fr_rq.ks + fr_rq.vs):
            assert np.array_equal(qa, qb)
            assert np.all(np.abs(sa.view(np.int32)
                                 - sb.view(np.int32)) <= 1)
        # decode with keep_bits: pages stay quantized, CRCs checked
        fr = frames.decode_frame(frame_q, keep_bits=bits)
        assert fr.quantized and fr.bits == bits
        for (qa, sa), (qb, sb) in zip(fr.ks + fr.vs, kqs + vqs):
            assert np.array_equal(qa, qb)
            assert np.array_equal(sa, sb)
        # adopt_quantized installs the sender's exact resident bits
        dst = _pool(model, kv_dtype, prefix_share=False)
        dst.adopt_quantized(0, fr.length, fr.ks, fr.vs)
        _, kqs2, vqs2 = dst.extract_quantized(0)
        for (qa, sa), (qb, sb) in zip(kqs + vqs, kqs2 + vqs2):
            assert np.array_equal(qa, qb)
            assert np.array_equal(sa, sb)
        # a mismatched keep_bits dequantizes as before
        fr_f = frames.decode_frame(frame_q, keep_bits=None)
        assert not fr_f.quantized
        assert fr_f.ks[0].dtype == np.float32

    def test_adopt_quantized_requires_quant_pool(self):
        model = _lm()
        pool = _pool(model, "f32")
        with pytest.raises(ValueError, match="quantized pool"):
            pool.extract_quantized(0)
        with pytest.raises(ValueError, match="quantized pool"):
            pool.adopt_quantized(0, 8, [], [])


# ---------------------------------------------------------------------------
# admission rejection + config plumbing
# ---------------------------------------------------------------------------


class TestAdmissionAndConfig:
    def test_tail_too_long_typed_rejection_no_state_change(self):
        """Regression: a tail longer than every bucket used to escape
        ``admit`` as a bare StopIteration from the bucket generator —
        AFTER the prefix pages were already refcounted. It must be a
        typed AdmissionRejected raised BEFORE any state change."""
        model = _lm()
        params = model.init(jax.random.PRNGKey(0))
        pool = _pool(model, "f32")
        free_before = pool.pool.free_pages
        prompt = np.arange(6, dtype=np.int32)
        with pytest.raises(AdmissionRejected,
                           match="exceeds the largest prefill bucket") \
                as ei:
            pool.admit(params, prompt, 0, (4,))
        assert ei.value.reason == "tail_too_long"
        assert pool.pool.free_pages == free_before
        assert pool.owned[0] == []
        assert int(pool.lengths[0]) == 0
        # the same slot still admits normally afterwards
        pool.admit(params, prompt, 0, BUCKETS)
        assert int(pool.lengths[0]) == 6

    def test_tail_too_long_after_prefix_hit_keeps_refcounts(self):
        """The dangerous variant: matched prefix pages must NOT stay
        refcounted when the tail rejects."""
        model = _lm()
        params = model.init(jax.random.PRNGKey(0))
        pool = _pool(model, "f32")
        shared = np.arange(16, dtype=np.int32)
        pool.admit(params, np.concatenate(
            [shared, np.arange(3, dtype=np.int32) + 40]), 0, BUCKETS)
        pool.release(0)
        refs_before = list(pool.pool.refcount)
        long_tail = np.concatenate(
            [shared, np.arange(9, dtype=np.int32) + 50])
        with pytest.raises(AdmissionRejected) as ei:
            pool.admit(params, long_tail, 1, (8,))   # tail 9 > 8
        assert ei.value.reason == "tail_too_long"
        assert list(pool.pool.refcount) == refs_before

    def test_non_paged_explicit_kv_dtype_raises(self):
        model = _lm()
        params = model.init(jax.random.PRNGKey(0))
        with pytest.raises(ValueError, match="paged"):
            InferenceEngine(model, params,
                            EngineConfig(kv_dtype="q8", max_len=MAX_LEN))
        # f32 explicitly is fine (it IS the contiguous pool's contract)
        InferenceEngine(model, params,
                        EngineConfig(kv_dtype="f32", max_len=MAX_LEN))

    def test_env_default_drives_paged_pool(self, monkeypatch):
        model = _lm()
        params = model.init(jax.random.PRNGKey(0))
        monkeypatch.setenv("DPX_SERVE_KV_DTYPE", "q8")
        eng = InferenceEngine(model, params, EngineConfig(
            paged=True, n_slots=2, max_len=MAX_LEN, page_len=L))
        assert eng.pool.kv_dtype == "q8"
        assert eng.pool.quant_bits == 8
        # non-paged engines ignore the env var (fleet-wide setting must
        # not break contiguous pools in the same process)
        eng2 = InferenceEngine(model, params,
                               EngineConfig(max_len=MAX_LEN))
        assert not hasattr(eng2.pool, "quant_bits") or \
            eng2.pool.__class__.__name__ == "SlotPool"

    def test_unknown_kv_dtype_raises(self):
        model = _lm()
        with pytest.raises(ValueError, match="kv_dtype"):
            _pool(model, "fp8")
