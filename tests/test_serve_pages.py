"""Paged, prefix-shared KV cache (serve/pages/) — the acceptance suite.

The headline contract extends PR 3's: for a mixed batch of COLD,
PARTIALLY shared, and FULLY shared prompts, every engine token stream
is bit-identical to a standalone ``generate()`` call — with exactly ONE
jitted decode program and one prefill program per tail-length bucket —
while shared full prefix pages are computed once, refcounted across
slots, LRU-evicted only at refcount zero, and pool exhaustion surfaces
as typed back-pressure (admission) or a typed, attributed per-request
failure (mid-decode growth) that never corrupts co-resident streams.
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_pytorch_tpu import models
from distributed_pytorch_tpu.models.generate import (make_generate_fn,
                                                     prefill_partial,
                                                     prefill_partial_paged)
from distributed_pytorch_tpu.runtime import faults
from distributed_pytorch_tpu.serve import (AdmissionRejected, EngineConfig,
                                           EngineStopped, InferenceEngine,
                                           PagePool, PagePoolExhausted,
                                           PrefixIndex,
                                           RequestDeadlineExceeded,
                                           SamplingParams)
from distributed_pytorch_tpu.serve.pages import PagedSlotPool
from distributed_pytorch_tpu.utils.logging import MetricsLogger

MAX_LEN = 64
L = 8  # page_len used by most engine tests


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.reset()
    yield
    faults.reset()


def _lm(**kw):
    kw.setdefault("vocab", 61)
    kw.setdefault("dim", 32)
    kw.setdefault("n_layers", 2)
    kw.setdefault("n_heads", 4)
    kw.setdefault("n_kv_heads", 2)
    kw.setdefault("pos", "rope")
    kw.setdefault("max_seq", 128)
    return models.TransformerLM(**kw)


def _lm1(**kw):
    kw.setdefault("n_layers", 1)
    return _lm(**kw)


def _standalone(model, params, prompt, sp, key, max_len=MAX_LEN):
    fn = make_generate_fn(model, sp.max_new_tokens,
                          temperature=sp.temperature, top_k=sp.top_k,
                          top_p=sp.top_p, max_len=max_len)
    return np.asarray(jax.jit(fn)(params, jnp.asarray(prompt[None]),
                                  key))[0]


def _paged_engine(model, params, **kw):
    kw.setdefault("n_slots", 3)
    kw.setdefault("max_len", MAX_LEN)
    kw.setdefault("page_len", L)
    return InferenceEngine(model, params, EngineConfig(paged=True, **kw))


# ---------------------------------------------------------------------------
# host-side policy units: PagePool + PrefixIndex
# ---------------------------------------------------------------------------


class TestPagePoolUnits:
    def test_refcount_free_list_lifecycle(self):
        pool = PagePool(4, 8)
        a = pool.take_free()
        b = pool.take_free()
        assert pool.refcount[a] == 1 and pool.free_pages == 2
        pool.incref(a)
        pool.decref(a)
        assert pool.free_pages == 2          # still referenced
        pool.decref(a)
        assert pool.free_pages == 3          # back on the free list
        with pytest.raises(ValueError, match="double release"):
            pool.decref(a)
        # an indexed page parks as RESIDENT at refcount zero, not free
        pool.indexed[b] = True
        pool.decref(b)
        assert pool.free_pages == 3 and pool.refcount[b] == 0

    def test_match_caps_and_partial_pages_never_indexed(self):
        pool = PagePool(8, 4)
        idx = PrefixIndex(4)
        toks = np.arange(14, dtype=np.int32)     # 3 full pages + 2 tail
        pages = [pool.take_free() for _ in range(4)]
        idx.insert(toks, 14 // 4, pages, pool)   # only 3 full pages
        assert len(idx) == 3
        assert not pool.indexed[pages[3]]        # the partial tail page
        # a shorter prompt that is a strict prefix: the lookup is capped
        # at (S-1)//L so the LAST full page is never consumed whole —
        # at least one token remains for the tail prefill
        assert idx.match(toks[:12], (12 - 1) // 4, pool) == pages[:2]
        assert idx.match(toks[:13], (13 - 1) // 4, pool) == pages[:3]
        # divergent second chunk stops the walk after one page
        other = toks.copy()
        other[5] += 1
        assert idx.match(other, 3, pool) == pages[:1]

    def test_evict_lru_leaf_first_never_live(self):
        pool = PagePool(8, 4)
        idx = PrefixIndex(4)
        live = np.arange(8, dtype=np.int32)
        cold = np.arange(8, dtype=np.int32) + 20
        live_pages = [pool.take_free() for _ in range(2)]
        cold_pages = [pool.take_free() for _ in range(2)]
        idx.insert(live, 2, live_pages, pool)
        idx.insert(cold, 2, cold_pages, pool)
        # cold chain fully released; live chain keeps its readers
        for p in cold_pages:
            pool.decref(p)
        # leaf first: depth-1 page goes before its parent, and the LIVE
        # chain is never a candidate no matter how stale its clock is
        assert idx.evict_lru(pool) == cold_pages[1]
        assert idx.evict_lru(pool) == cold_pages[0]
        assert idx.evict_lru(pool) is None
        assert all(pool.refcount[p] == 1 for p in live_pages)
        assert pool.evictions == 2

    def test_page_fault_ops_registered(self):
        assert "page_admit" in faults.COMM_OPS
        assert "page_evict" in faults.COMM_OPS
        specs = faults.parse_fault_spec(
            "delay@op=page_admit,ms=5;kill@op=page_evict,call=2")
        assert specs[0].op == "page_admit" and specs[1].op == "page_evict"


# ---------------------------------------------------------------------------
# the paged ops (models/generate.py)
# ---------------------------------------------------------------------------


class TestPagedOps:
    @pytest.mark.slow
    def test_cold_paged_prefill_matches_prefill_partial(self):
        """offset=0 through the paged program computes the same last-
        position logits as the contiguous prefill_partial (pad tail and
        fully-masked prefix both causally inert)."""
        model = _lm()
        params = model.init(jax.random.PRNGKey(0))
        rng = np.random.default_rng(0)
        s, bucket, page_len, n_pages = 11, 16, 4, 8
        prompt = rng.integers(0, 61, (s,)).astype(np.int32)
        padded = jnp.zeros((1, bucket), jnp.int32).at[0, :s].set(prompt)
        ref, _, _ = jax.jit(
            lambda p, t, n: prefill_partial(model, p, t, n))(
            params, padded, s)
        dh = model.dim // model.n_heads
        shape = (n_pages, model.n_kv_heads, page_len, dh)
        kp = [jnp.zeros(shape, model.dtype) for _ in range(model.n_layers)]
        vp = [jnp.zeros(shape, model.dtype) for _ in range(model.n_layers)]
        table = jnp.arange(4, dtype=jnp.int32)
        got, _, _ = jax.jit(
            lambda p, k, v, tr, t, o, n: prefill_partial_paged(
                model, p, k, v, tr, t, o, n, page_len=page_len))(
            params, kp, vp, table, padded, 0, s)
        np.testing.assert_allclose(np.asarray(ref), np.asarray(got),
                                   rtol=2e-5, atol=2e-6)
        assert int(jnp.argmax(ref)) == int(jnp.argmax(got))


# ---------------------------------------------------------------------------
# the paged engine
# ---------------------------------------------------------------------------


class TestPagedEngine:
    def test_shared_mix_bit_identical(self):
        """The tier-1 acceptance kernel: a cold, a partially shared,
        and a fully shared prompt through the paged engine — every
        stream equals standalone generate(), ONE decode compile, one
        prefill per tail bucket, hit accounting exact. Deliberately
        compile-lean (1 layer, one prompt length, one sampler → a
        single standalone reference program) so tier-1 stays near the
        seed's budget; the wider staggered 2-layer mix with mixed
        sampling runs in the slow tier, and serve_bench --smoke
        re-asserts this contract in CI on every push."""
        model = _lm1()
        params = model.init(jax.random.PRNGKey(0))
        rng = np.random.default_rng(12)
        eng = _paged_engine(model, params, n_slots=3)
        pfx = rng.integers(0, 61, (16,)).astype(np.int32)   # 2 full pages
        prompts = [
            np.concatenate([pfx, rng.integers(0, 61, (4,))]).astype(np.int32),
            np.concatenate([pfx, rng.integers(0, 61, (4,))]).astype(np.int32),
            None,
        ]
        prompts[2] = prompts[0].copy()                      # full share
        sp = SamplingParams(max_new_tokens=8)
        keys = [jax.random.PRNGKey(100 + i) for i in range(3)]
        fn = jax.jit(make_generate_fn(model, sp.max_new_tokens,
                                      max_len=MAX_LEN))
        with eng:
            hs = [eng.submit(prompts[i], sp, rng=keys[i])
                  for i in range(3)]
            outs = [h.result(timeout=120) for h in hs]
        for i in range(3):
            ref = np.asarray(fn(params, jnp.asarray(prompts[i][None]),
                                keys[i]))[0]
            np.testing.assert_array_equal(outs[i], ref,
                                          err_msg=f"request {i}")
        st = eng.stats()
        assert st["decode_compiles"] == 1, st
        assert all(v == 1 for v in st["prefill_compiles"].values()), st
        assert [h.metrics["prefix_hit_pages"] for h in hs] == [0, 2, 2]
        assert [h.metrics["prefill_tokens_saved"] for h in hs] == [0, 16, 16]

    # slow tier: the staggered 2-layer wide mix (five standalone
    # generate compiles); the contract kernel above stays tier-1 and
    # serve_bench --smoke re-asserts it in CI on every push
    @pytest.mark.slow
    def test_mixed_cold_partial_full_bit_identical(self):
        """THE acceptance case: cold / partially shared / fully shared /
        sub-page prompts, staggered admission past the slot count, mixed
        sampling — every stream equals standalone generate(), decode
        compiles once, one prefill per tail bucket, and the hit
        accounting matches the share structure exactly."""
        model = _lm()
        params = model.init(jax.random.PRNGKey(0))
        rng = np.random.default_rng(0)
        eng = _paged_engine(model, params, n_slots=3)
        pfx = rng.integers(0, 61, (16,)).astype(np.int32)   # 2 full pages
        prompts = [
            np.concatenate([pfx, rng.integers(0, 61, (4,))]).astype(np.int32),
            np.concatenate([pfx, rng.integers(0, 61, (9,))]).astype(np.int32),
            None,                                           # dup of 0
            rng.integers(0, 61, (5,)).astype(np.int32),     # sub-page cold
            np.concatenate([pfx[:8], rng.integers(0, 61, (6,))]).astype(np.int32),
        ]
        prompts[2] = prompts[0].copy()
        sps = [SamplingParams(max_new_tokens=24),
               SamplingParams(max_new_tokens=5, temperature=0.7, top_k=8),
               SamplingParams(max_new_tokens=8),
               SamplingParams(max_new_tokens=6, temperature=0.9, top_p=0.9),
               SamplingParams(max_new_tokens=6)]
        keys = [jax.random.PRNGKey(100 + i) for i in range(5)]
        with eng:
            hs = [eng.submit(prompts[i], sps[i], rng=keys[i])
                  for i in range(4)]
            hs[1].result(timeout=120)     # slot frees mid-run
            hs.append(eng.submit(prompts[4], sps[4], rng=keys[4]))
            outs = [h.result(timeout=120) for h in hs]
        for i in range(5):
            ref = _standalone(model, params, prompts[i], sps[i], keys[i])
            np.testing.assert_array_equal(outs[i], ref,
                                          err_msg=f"request {i}")
        st = eng.stats()
        assert st["decode_compiles"] == 1, st
        assert all(v == 1 for v in st["prefill_compiles"].values()), st
        hits = [h.metrics["prefix_hit_pages"] for h in hs]
        saved = [h.metrics["prefill_tokens_saved"] for h in hs]
        # 0 cold; 1 shares both prefix pages; 2 (identical prompt, len
        # 20) shares both; 3 has no full page; 4 shares only page 0
        assert hits == [0, 2, 2, 0, 1], (hits, st["pages"])
        assert saved == [0, 16, 16, 0, 8]
        # overlap really happened: request 0 (24 tokens) outlived 1's
        # retirement, and everything was bit-exact anyway
        assert (hs[0].metrics["retire_iteration"]
                > hs[1].metrics["retire_iteration"])

    @pytest.mark.slow
    def test_prefix_longer_than_resident_entry(self):
        """A prompt that is a strict PREFIX of a resident chain: the
        match is capped at the request's own (S-1)//L full pages, so
        the tail prefill always has at least one real token
        (slow tier: five standalone-generate compiles; the cap math is
        also covered by TestPagePoolUnits::test_match_caps... in the
        fast tier)."""
        model = _lm()
        params = model.init(jax.random.PRNGKey(0))
        rng = np.random.default_rng(3)
        long = rng.integers(0, 61, (33,)).astype(np.int32)  # 4 full pages
        cases = [(17, 2), (16, 1), (8, 0), (5, 0)]
        eng = _paged_engine(model, params, n_slots=2)
        with eng:
            h0 = eng.submit(long, SamplingParams(max_new_tokens=4),
                            rng=jax.random.PRNGKey(0))
            h0.result(timeout=120)
            for s, want_hit in cases:
                sp = SamplingParams(max_new_tokens=4)
                key = jax.random.PRNGKey(s)
                h = eng.submit(long[:s], sp, rng=key)
                out = h.result(timeout=120)
                ref = _standalone(model, params, long[:s], sp, key)
                np.testing.assert_array_equal(out, ref, err_msg=f"S={s}")
                assert h.metrics["prefix_hit_pages"] == want_hit, s

    @pytest.mark.slow   # divergent-chunk cap is tier-1 via test_match_caps
    def test_partial_page_tail_never_shared(self):
        """Two prompts agreeing on 12 tokens share exactly the one FULL
        page (8 tokens) — the 4-token partial tail is private."""
        model = _lm1()
        params = model.init(jax.random.PRNGKey(0))
        rng = np.random.default_rng(4)
        common = rng.integers(0, 61, (12,)).astype(np.int32)
        a = np.concatenate([common, rng.integers(0, 61, (3,))]).astype(np.int32)
        b = np.concatenate([common, rng.integers(0, 61, (5,))]).astype(np.int32)
        eng = _paged_engine(model, params, n_slots=2)
        with eng:
            ka, kb = jax.random.PRNGKey(1), jax.random.PRNGKey(2)
            sp = SamplingParams(max_new_tokens=5)
            ha = eng.submit(a, sp, rng=ka)
            ha.result(timeout=120)
            hb = eng.submit(b, sp, rng=kb)
            np.testing.assert_array_equal(
                hb.result(timeout=120), _standalone(model, params, b, sp, kb))
        assert ha.metrics["prefix_hit_pages"] == 0
        assert hb.metrics["prefix_hit_pages"] == 1
        assert hb.metrics["prefill_tokens_saved"] == 8

    @pytest.mark.slow   # release-path coverage is tier-1 via crash-drain + chaos
    def test_refcount_release_on_retirement(self):
        """After every request retires, no page has a live reader;
        indexed prompt pages stay RESIDENT (evictable), private pages
        return to the free list."""
        model = _lm1()
        params = model.init(jax.random.PRNGKey(0))
        rng = np.random.default_rng(5)
        eng = _paged_engine(model, params, n_slots=2)
        with eng:
            for i in range(3):
                prompt = rng.integers(0, 61, (18,)).astype(np.int32)
                eng.submit(prompt, SamplingParams(max_new_tokens=6),
                           rng=jax.random.PRNGKey(i)).result(timeout=120)
        pool = eng.pool.pool
        assert pool.live_pages() == 0
        assert len(eng.pool.index) == pool.pages_in_use
        assert pool.free_pages + pool.pages_in_use == pool.n_pages

    def test_refcount_release_on_crash_drain(self):
        """An engine-loop crash fails futures typed AND drops every page
        reference — a dead engine cannot pin pool pages."""
        model = _lm1()
        params = model.init(jax.random.PRNGKey(0))
        eng = _paged_engine(model, params, n_slots=2)

        def boom(*a, **k):
            raise RuntimeError("injected engine bug")
        eng.pool.decode = boom
        eng.start()
        h = eng.submit(np.arange(10, dtype=np.int32),
                       SamplingParams(max_new_tokens=8))
        with pytest.raises(EngineStopped):
            h.result(timeout=60)
        eng.shutdown()
        assert eng.pool.pool.live_pages() == 0

    # slow tier: the deadline path is tier-1 in test_serve.py and the
    # release path is tier-1 via the chaos + crash-drain cases
    @pytest.mark.slow
    def test_midstream_failure_releases_and_others_unharmed(self):
        """A queued-deadline failure mid-run releases the victim's
        references while the co-resident stream stays bit-exact."""
        model = _lm1()
        params = model.init(jax.random.PRNGKey(0))
        rng = np.random.default_rng(6)
        prompt = rng.integers(0, 61, (9,)).astype(np.int32)
        key = jax.random.PRNGKey(3)
        sp = SamplingParams(max_new_tokens=30)
        eng = _paged_engine(model, params, n_slots=1)
        with eng:
            ha = eng.submit(prompt, sp, rng=key)
            hb = eng.submit(np.arange(4, dtype=np.int32),
                            SamplingParams(max_new_tokens=4,
                                           deadline_ms=40.0))
            with pytest.raises(RequestDeadlineExceeded):
                hb.result(timeout=60)
            np.testing.assert_array_equal(
                ha.result(timeout=120),
                _standalone(model, params, prompt, sp, key))
        assert eng.pool.pool.live_pages() == 0

    # slow tier: the LRU/liveness invariants are unit-tested tier-1 and
    # eviction-under-load is also exercised by the backpressure test
    @pytest.mark.slow
    def test_eviction_pressure_admissions_evict_lru_only(self):
        """Distinct prompts churn a small pool: refcount-zero indexed
        pages are LRU-evicted to make room, a LIVE long-running request
        is never a victim, and its stream stays bit-exact."""
        model = _lm1()
        params = model.init(jax.random.PRNGKey(0))
        rng = np.random.default_rng(7)
        # pool: 8 pages of 4 — a live request + churn must evict
        eng = _paged_engine(model, params, n_slots=2, max_len=32,
                            page_len=4, n_pages=8)
        long_prompt = rng.integers(0, 61, (8,)).astype(np.int32)
        key = jax.random.PRNGKey(9)
        sp_long = SamplingParams(max_new_tokens=20)
        with eng:
            hl = eng.submit(long_prompt, sp_long, rng=key)
            churn = []
            for i in range(5):
                p = rng.integers(0, 61, (9,)).astype(np.int32)
                churn.append((p, jax.random.PRNGKey(20 + i)))
                eng.submit(p, SamplingParams(max_new_tokens=2),
                           rng=churn[-1][1]).result(timeout=120)
            out = hl.result(timeout=120)
        np.testing.assert_array_equal(
            out, _standalone(model, params, long_prompt, sp_long, key,
                             max_len=32))
        assert eng.pool.pool.evictions > 0
        assert eng.pool.pool.live_pages() == 0

    def test_chaos_pool_exhaustion_mid_decode_typed_victim(self):
        """THE chaos satellite: every page held by a live reader when a
        slot's decode crosses a page boundary — the victim fails with a
        typed, attributed PagePoolExhausted (request + iteration) while
        the co-resident stream is bit-identical to generate(), and the
        page-op fault grammar demonstrably fired."""
        model = _lm1()
        params = model.init(jax.random.PRNGKey(0))
        rng = np.random.default_rng(8)
        faults.install("delay@op=page_admit,call=1,ms=1")
        eng = _paged_engine(model, params, n_slots=2, max_len=16,
                            page_len=4, n_pages=4)
        a = rng.integers(0, 61, (4,)).astype(np.int32)   # 1 page
        b = rng.integers(0, 61, (8,)).astype(np.int32)   # 2 pages
        ka, kb = jax.random.PRNGKey(1), jax.random.PRNGKey(2)
        sp_a = SamplingParams(max_new_tokens=4)   # grows to page 1, stops
        sp_b = SamplingParams(max_new_tokens=6)   # needs page 2 mid-decode
        with eng:
            ha = eng.submit(a, sp_a, rng=ka)
            hb = eng.submit(b, sp_b, rng=kb)
            with pytest.raises(PagePoolExhausted) as ei:
                hb.result(timeout=120)
            out_a = ha.result(timeout=120)
        assert ei.value.request_id == hb.request_id
        assert ei.value.iteration is not None
        assert ei.value.free_pages == 0
        np.testing.assert_array_equal(
            out_a, _standalone(model, params, a, sp_a, ka, max_len=16))
        assert any(f.startswith("delay@op=page_admit")
                   for f in faults.fired()), faults.fired()
        # the victim's references were dropped with it
        assert eng.pool.pool.live_pages() == 0

    @pytest.mark.slow   # exhaustion-with-typed-failure is tier-1 via the chaos case
    def test_admission_backpressure_requeues_then_serves(self):
        """Admission that cannot get pages while another request runs
        stays QUEUED (typed back-pressure, FCFS-stable) and is served
        bit-exactly once the retirement frees pages."""
        model = _lm1()
        params = model.init(jax.random.PRNGKey(0))
        rng = np.random.default_rng(9)
        eng = _paged_engine(model, params, n_slots=2, max_len=12,
                            page_len=4, n_pages=3)
        a = rng.integers(0, 61, (8,)).astype(np.int32)
        b = rng.integers(0, 61, (8,)).astype(np.int32)
        ka, kb = jax.random.PRNGKey(1), jax.random.PRNGKey(2)
        sp = SamplingParams(max_new_tokens=4)
        with eng:
            ha = eng.submit(a, sp, rng=ka)
            hb = eng.submit(b, sp, rng=kb)
            out_a = ha.result(timeout=120)
            out_b = hb.result(timeout=120)
        np.testing.assert_array_equal(
            out_a, _standalone(model, params, a, sp, ka, max_len=12))
        np.testing.assert_array_equal(
            out_b, _standalone(model, params, b, sp, kb, max_len=12))
        # b could only start after a's retirement freed pages
        assert (hb.metrics["admit_iteration"]
                >= ha.metrics["retire_iteration"])
        assert eng.pool.pool.evictions > 0   # a's indexed pages reclaimed

    def test_submit_rejects_worst_case_page_need(self):
        model = _lm1()
        params = model.init(jax.random.PRNGKey(0))
        eng = _paged_engine(model, params, n_slots=1, max_len=32,
                            page_len=4, n_pages=2)
        with pytest.raises(AdmissionRejected) as ei:
            eng.submit(np.arange(10, dtype=np.int32),
                       SamplingParams(max_new_tokens=10))
        assert ei.value.reason == "no_free_pages"
        eng.shutdown(wait=False)

    @pytest.mark.slow
    def test_prefix_share_off_still_bit_exact(self):
        """DPX_SERVE_PREFIX_SHARE=0 semantics: paged layout, zero hits,
        streams still equal generate()."""
        model = _lm1()
        params = model.init(jax.random.PRNGKey(0))
        rng = np.random.default_rng(10)
        prompt = rng.integers(0, 61, (18,)).astype(np.int32)
        eng = _paged_engine(model, params, n_slots=2, prefix_share=False)
        sp = SamplingParams(max_new_tokens=6)
        with eng:
            hs = [eng.submit(prompt, sp, rng=jax.random.PRNGKey(i))
                  for i in range(2)]
            outs = [h.result(timeout=120) for h in hs]
        for i, h in enumerate(hs):
            np.testing.assert_array_equal(
                outs[i], _standalone(model, params, prompt, sp,
                                     jax.random.PRNGKey(i)))
            assert h.metrics["prefix_hit_pages"] == 0
        assert len(eng.pool.index) == 0

    def test_windowed_model_rejects_paged(self):
        from distributed_pytorch_tpu.nn.attention import dense_attention

        def fn(q, k, v, *, causal=False, scale=None):
            return dense_attention(q, k, v, causal=causal, scale=scale,
                                   window=8)
        fn.window = 8
        model = _lm1(vocab=64, attn_fn=fn)
        params = model.init(jax.random.PRNGKey(0))
        with pytest.raises(ValueError, match="sliding-window"):
            _paged_engine(model, params)

    @pytest.mark.slow   # hit-rate/occupancy flow also CI-gated by serve_bench --smoke
    def test_paged_metrics_flow_to_logger(self, tmp_path):
        """serve_request events carry the prefix fields; periodic
        engine rows carry pool occupancy and hit rate; the fleet
        aggregate sums prefill_tokens_saved."""
        from distributed_pytorch_tpu.serve import aggregate
        model = _lm1()
        params = model.init(jax.random.PRNGKey(0))
        rng = np.random.default_rng(11)
        log = tmp_path / "serve_pages.jsonl"
        logger = MetricsLogger(path=str(log))
        eng = InferenceEngine(model, params, EngineConfig(
            n_slots=2, max_len=MAX_LEN, paged=True, page_len=L,
            metrics=logger, log_every=2))
        pfx = rng.integers(0, 61, (16,)).astype(np.int32)
        with eng:
            hs = [eng.submit(
                np.concatenate([pfx,
                                rng.integers(0, 61, (3,))]).astype(np.int32),
                SamplingParams(max_new_tokens=6),
                rng=jax.random.PRNGKey(i)) for i in range(3)]
            for h in hs:
                h.result(timeout=120)
        logger.close()
        rows = [json.loads(ln) for ln in log.read_text().splitlines()]
        reqs = [r for r in rows if r.get("event") == "serve_request"]
        assert len(reqs) == 3
        assert sorted(r["prefix_hit_pages"] for r in reqs) == [0, 2, 2]
        assert sorted(r["prefill_tokens_saved"] for r in reqs) == [0, 16, 16]
        engine_rows = [r for r in rows
                       if r.get("event") == "metrics_snapshot"
                       and r.get("source") == "serve_engine"]
        assert engine_rows
        for r in engine_rows:
            m = r["metrics"]
            assert 0.0 <= m["serve.pool_occupancy"] <= 1.0
            assert "serve.free_pages" in m
            assert "serve.page_evictions" in m
        agg = aggregate([h.metrics for h in hs])
        assert agg["prefill_tokens_saved"] == 32
        assert 0.0 < agg["prefix_hit_rate"] < 1.0
        assert agg["prefix_hit_pages"] == 4
