"""Speculative decoding (``serve/spec/``; docs/serving.md "Speculative
decoding").

What must hold (ISSUE 19):

- the GREEDY CONTRACT: the accepted token stream is bit-identical to
  ``generate()``'s for the contiguous pool, the paged pool, and the
  disaggregated split — speculation is a latency optimization, never a
  behavior change. In a quantized (q8) pool the reference is the same
  engine WITHOUT speculation: the pool's argmax stream is whatever the
  quantized cache produces, and spec must reproduce it exactly;
- ONE verify and one commit program per draft-length bucket
  (``CompileCounts.verify`` / ``.commit``), asserted, not trusted;
- acceptance extremes are exact: a self-draft on matching pool layouts
  accepts everything (rate 1.0, k+1 tokens per iteration), an
  all-zeros draft whose constant proposal never appears in the target
  stream accepts nothing (rate 0.0, 1 token per iteration) — and both
  are STILL bit-exact, because acceptance only affects speed;
- rollback never corrupts the quantize-once discipline: a rejection at
  a page boundary leaves the next page unallocated and unquantized, a
  partially-filled page stays in the exact f32 tail until an ACCEPTED
  token completes it;
- failures are contained: ``flaky@op=spec_verify`` fails ONLY the
  speculating victim (typed ``SpecDecodeError``, request + iteration +
  stage attributed) while a co-resident non-spec stream stays
  bit-identical to its standalone reference; an injected verify delay
  trips the victim's OWN deadline, typed;
- the per-tenant quota front door: the (max+1)-th inflight submit for
  a tenant is rejected synchronously (``reason="tenant_quota"``,
  tenant attributed) and the credit returns at retirement.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from distributed_pytorch_tpu import models
from distributed_pytorch_tpu.models.generate import (generate,
                                                     make_generate_fn)
from distributed_pytorch_tpu.runtime import faults
from distributed_pytorch_tpu.serve import (AdmissionRejected,
                                           DisaggConfig, DisaggEngine,
                                           EngineConfig, InferenceEngine,
                                           RequestDeadlineExceeded,
                                           SamplingParams,
                                           SpecDecodeError, aggregate)
from distributed_pytorch_tpu.serve.pages import PagedSlotPool

MAX_LEN = 64
BUCKETS = (8, 16, 32)
L = 8


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.reset()
    yield
    faults.reset()


def _lm(**kw):
    kw.setdefault("vocab", 61)
    kw.setdefault("dim", 32)
    kw.setdefault("n_layers", 2)
    kw.setdefault("n_heads", 4)
    kw.setdefault("n_kv_heads", 2)
    kw.setdefault("pos", "rope")
    kw.setdefault("max_seq", 128)
    return models.TransformerLM(**kw)


def _lm1(**kw):
    kw.setdefault("n_layers", 1)
    return _lm(**kw)


def _draft(**kw):
    """The cheap proposer: same vocab, a fraction of the stack."""
    kw.setdefault("dim", 16)
    kw.setdefault("n_layers", 1)
    kw.setdefault("n_heads", 2)
    kw.setdefault("n_kv_heads", 1)
    return _lm(**kw)


def _spec_cfg(dm, dp, **kw):
    kw.setdefault("n_slots", 4)
    kw.setdefault("max_len", MAX_LEN)
    kw.setdefault("buckets", BUCKETS)
    return EngineConfig(spec_decode=True, draft_model=dm,
                        draft_params=dp, draft_len=3, **kw)


def _prompts():
    """Cold + shared-prefix mix: the last two share their first 8
    tokens (one full page), so the paged runs exercise prefix reuse
    under speculation."""
    base = np.arange(1, 25, dtype=np.int32) % 61
    return [base[:5].copy(), base[:13].copy(),
            np.concatenate([base[:8], base[8:11] * 0 + 7]),
            np.concatenate([base[:8], base[8:12] * 0 + 9])]


def _standalone(model, params, prompt, sp, key):
    fn = make_generate_fn(model, sp.max_new_tokens,
                          temperature=sp.temperature, top_k=sp.top_k,
                          top_p=sp.top_p, max_len=MAX_LEN)
    return np.asarray(jax.jit(fn)(params, jnp.asarray(prompt[None]),
                                  key))[0]


# ---------------------------------------------------------------------------
# the acceptance rule itself (pure host code)
# ---------------------------------------------------------------------------


class TestAcceptGreedy:
    def _logits(self, g, vocab=16):
        """Verify logits whose per-position argmax is ``g``."""
        lg = np.zeros((len(g), vocab), np.float32)
        lg[np.arange(len(g)), g] = 1.0
        return lg

    def test_full_acceptance_emits_k_plus_one(self):
        from distributed_pytorch_tpu.serve.spec import accept_greedy
        g = np.array([3, 5, 7, 9], np.int32)    # k = 3
        out, e = accept_greedy(g[:3], self._logits(g), 10, None)
        assert e == 4 and out == [3, 5, 7, 9]   # bonus token rides free

    def test_first_mismatch_truncates(self):
        from distributed_pytorch_tpu.serve.spec import accept_greedy
        g = np.array([3, 5, 7, 9], np.int32)
        drafts = np.array([3, 6, 7], np.int32)  # d_2 wrong
        out, e = accept_greedy(drafts, self._logits(g), 10, None)
        assert e == 2 and out == [3, 5]

    def test_remaining_caps_acceptance(self):
        from distributed_pytorch_tpu.serve.spec import accept_greedy
        g = np.array([3, 5, 7, 9], np.int32)
        out, e = accept_greedy(g[:3], self._logits(g), 2, None)
        assert e == 2 and out == [3, 5]

    def test_eos_truncates_inclusive(self):
        from distributed_pytorch_tpu.serve.spec import accept_greedy
        g = np.array([3, 5, 7, 9], np.int32)
        out, e = accept_greedy(g[:3], self._logits(g), 10, 5)
        assert e == 2 and out == [3, 5]         # eos kept, suffix cut


# ---------------------------------------------------------------------------
# the greedy bit-exact contract
# ---------------------------------------------------------------------------


class TestGreedyContract:
    @pytest.mark.parametrize("pool_kw", [
        {}, {"paged": True}, {"paged": True, "kv_dtype": "q8"},
    ], ids=["contig", "paged", "q8"])
    def test_stream_matches_reference(self, pool_kw):
        """Spec output == the SAME engine's non-spec output; for exact
        pools that is ``generate()`` itself, for q8 it is a non-spec
        q8 engine (speculation must be invisible at every kv_dtype)."""
        model = _lm()
        params = model.init(jax.random.PRNGKey(0))
        dm = _draft()
        dp = dm.init(jax.random.PRNGKey(1))
        prompts = _prompts()
        n = 12
        if pool_kw.get("kv_dtype"):
            refs = []
            ref_eng = InferenceEngine(model, params, EngineConfig(
                n_slots=4, max_len=MAX_LEN, buckets=BUCKETS, **pool_kw))
            with ref_eng:
                hs = [ref_eng.submit(p, SamplingParams(max_new_tokens=n))
                      for p in prompts]
                refs = [np.asarray(h.result(timeout=120)) for h in hs]
        else:
            refs = [np.asarray(generate(model, params,
                                        jnp.asarray(p[None]), n)[0])
                    for p in prompts]
        eng = InferenceEngine(model, params,
                              _spec_cfg(dm, dp, **pool_kw))
        with eng:
            hs = [eng.submit(p, SamplingParams(max_new_tokens=n),
                             tenant="acme")
                  for p in prompts]
            outs = [np.asarray(h.result(timeout=120)) for h in hs]
        for out, ref in zip(outs, refs):
            np.testing.assert_array_equal(out, ref)
        st = eng.stats()
        assert st["spec_decode"] is True
        sp = st["spec"]
        # ONE verify + ONE commit program for the single k+1=4 bucket
        assert sp["verify_compiles"] == {4: 1}
        assert sp["commit_compiles"] == {4: 1}
        assert sp["proposed"] > 0
        # per-request accounting rides the SLO record + aggregate view
        recs = [h.metrics for h in hs]
        assert all(r["tenant"] == "acme" for r in recs)
        assert sum(r["spec_proposed"] for r in recs) == sp["proposed"]
        agg = aggregate(recs)
        assert agg["spec_proposed"] == sp["proposed"]
        assert 0.0 <= agg["spec_acceptance_rate"] <= 1.0

    def test_disagg_stream_matches_generate(self):
        """The same contract across the prefill/decode split: the
        draft lives on the decode side and the accepted stream is
        bit-identical to ``generate()`` through the handoff."""
        model = _lm()
        params = model.init(jax.random.PRNGKey(0))
        dm = _draft()
        dp = dm.init(jax.random.PRNGKey(1))
        prompts = _prompts()
        n = 12
        refs = [np.asarray(generate(model, params,
                                    jnp.asarray(p[None]), n)[0])
                for p in prompts]
        eng = DisaggEngine(model, params, DisaggConfig(
            n_slots=4, max_len=MAX_LEN, buckets=BUCKETS,
            spec_decode=True, draft_model=dm, draft_params=dp,
            draft_len=3))
        with eng:
            hs = [eng.submit(p, SamplingParams(max_new_tokens=n))
                  for p in prompts]
            outs = [np.asarray(h.result(timeout=120)) for h in hs]
        for out, ref in zip(outs, refs):
            np.testing.assert_array_equal(out, ref)
        d = eng.stats()["decode"]
        assert d["spec"]["verify_compiles"] == {4: 1}
        assert d["prefill_compiles"] == {}     # the split held

    def test_mixed_spec_and_sampled_batch(self):
        """Spec (greedy) and non-spec (sampled) requests share the
        batch: the sampled stream is bit-identical to its standalone
        reference — speculation next door is invisible."""
        model = _lm()
        params = model.init(jax.random.PRNGKey(0))
        dm = _draft()
        dp = dm.init(jax.random.PRNGKey(1))
        prompts = _prompts()
        n = 10
        ref_g = np.asarray(generate(model, params,
                                    jnp.asarray(prompts[0][None]),
                                    n)[0])
        sp_s = SamplingParams(max_new_tokens=n, temperature=0.7,
                              top_k=8)
        key = jax.random.PRNGKey(5)
        ref_s = _standalone(model, params, prompts[1], sp_s, key)
        eng = InferenceEngine(model, params, _spec_cfg(dm, dp))
        with eng:
            hg = eng.submit(prompts[0],
                            SamplingParams(max_new_tokens=n))
            hs = eng.submit(prompts[1], sp_s, rng=key)
            out_g = np.asarray(hg.result(timeout=120))
            out_s = np.asarray(hs.result(timeout=120))
        np.testing.assert_array_equal(out_g, ref_g)
        np.testing.assert_array_equal(out_s, ref_s)
        st = eng.stats()["spec"]
        assert st["proposed"] > 0              # the greedy row DID spec


# ---------------------------------------------------------------------------
# acceptance extremes — exact, and still bit-exact
# ---------------------------------------------------------------------------


class TestAcceptanceExtremes:
    def test_self_draft_accepts_everything(self):
        """Draft == target on the SAME (contiguous) pool layout: every
        proposal matches, rate is exactly 1.0 and every iteration
        commits k+1 tokens. max_new = 1 + 3*(k+1) so no iteration is
        truncated by the remaining budget."""
        model = _lm()
        params = model.init(jax.random.PRNGKey(0))
        prompts = _prompts()[:2]
        n = 13
        refs = [np.asarray(generate(model, params,
                                    jnp.asarray(p[None]), n)[0])
                for p in prompts]
        eng = InferenceEngine(model, params,
                              _spec_cfg(model, params, n_slots=2))
        with eng:
            hs = [eng.submit(p, SamplingParams(max_new_tokens=n))
                  for p in prompts]
            outs = [np.asarray(h.result(timeout=120)) for h in hs]
        for out, ref in zip(outs, refs):
            np.testing.assert_array_equal(out, ref)
        st = eng.stats()["spec"]
        assert st["acceptance_rate"] == 1.0
        assert st["tokens_per_iteration"] == 4.0

    def test_zero_draft_accepts_nothing(self):
        """An all-zeros draft proposes token 0 forever; the target's
        greedy stream never contains 0 (asserted precondition), so the
        rate is exactly 0.0, each iteration commits exactly the ONE
        verified token — and the stream is still bit-exact, just not
        faster."""
        model = _lm()
        params = model.init(jax.random.PRNGKey(0))
        dp0 = jax.tree_util.tree_map(jnp.zeros_like, params)
        prompts = _prompts()[:2]
        n = 13
        refs = [np.asarray(generate(model, params,
                                    jnp.asarray(p[None]), n)[0])
                for p in prompts]
        for p, r in zip(prompts, refs):
            assert not (r[len(p):] == 0).any()   # precondition
        eng = InferenceEngine(model, params,
                              _spec_cfg(model, dp0, n_slots=2))
        with eng:
            hs = [eng.submit(p, SamplingParams(max_new_tokens=n))
                  for p in prompts]
            outs = [np.asarray(h.result(timeout=120)) for h in hs]
        for out, ref in zip(outs, refs):
            np.testing.assert_array_equal(out, ref)
        st = eng.stats()["spec"]
        assert st["acceptance_rate"] == 0.0
        assert st["tokens_per_iteration"] == 1.0


# ---------------------------------------------------------------------------
# rollback edges
# ---------------------------------------------------------------------------


class TestRollbackEdges:
    def test_page_boundary_rejection_never_quantizes_partial(self):
        """Pool-level q8: acceptance that ends exactly at a page
        boundary quantizes THAT page (complete, from accepted tokens)
        and leaves the next page unallocated; a later commit that only
        starts the next page leaves it in the exact f32 tail with its
        quant scales untouched."""
        model = _lm1()
        params = model.init(jax.random.PRNGKey(0))
        pool = PagedSlotPool(model, 1, MAX_LEN, page_len=L, n_pages=8,
                             kv_dtype="q8")
        prompt = (np.arange(1, 7, dtype=np.int32) % 61)   # 6 tokens
        pool.admit(params, prompt, 0, BUCKETS)
        pid0 = pool.owned[0][0]
        ones = np.ones_like(np.asarray(pool.k_scales[0][pid0]))
        # page 0 incomplete: still tail-resident, scales untouched
        np.testing.assert_array_equal(
            np.asarray(pool.k_scales[0][pid0]), ones)
        toks = np.array([[2, 3, 4, 5]], np.int32)
        _, sk, sv = pool.spec_verify(params, toks)
        # accept 2 of 4: positions 6,7 — ends EXACTLY at the boundary,
        # drafts for positions 8,9 rejected
        pool.ensure_spec_capacity(0, 2)
        pool.spec_commit(sk, sv, np.array([2], np.int32))
        assert int(pool.lengths[0]) == 8
        # page 0 completed from accepted tokens → quantized now
        assert not np.array_equal(
            np.asarray(pool.k_scales[0][pid0]), ones)
        # the rejected suffix never demanded (or touched) page 1
        assert len(pool.owned[0]) == 1
        # next iteration: accept ONE token into a fresh page — it must
        # stay in the f32 tail, unquantized, until the page completes
        _, sk, sv = pool.spec_verify(params, toks)
        pool.ensure_spec_capacity(0, 1)
        pool.spec_commit(sk, sv, np.array([1], np.int32))
        assert int(pool.lengths[0]) == 9
        pid1 = pool.owned[0][1]
        np.testing.assert_array_equal(
            np.asarray(pool.k_scales[0][pid1]), ones)
        assert np.abs(np.asarray(pool.k_tail[0][0, :, 0, :])).sum() > 0

    def test_draft_len_longer_than_remaining(self):
        """k = 6 against max_new = 3: acceptance is capped by the
        remaining budget every iteration, the stream is exact, and the
        request retires at exactly max_new tokens."""
        model = _lm1()
        params = model.init(jax.random.PRNGKey(0))
        dm = _draft()
        dp = dm.init(jax.random.PRNGKey(1))
        prompt = _prompts()[0]
        n = 3
        ref = np.asarray(generate(model, params,
                                  jnp.asarray(prompt[None]), n)[0])
        eng = InferenceEngine(model, params, EngineConfig(
            n_slots=2, max_len=MAX_LEN, buckets=BUCKETS,
            spec_decode=True, draft_model=dm, draft_params=dp,
            draft_len=6))
        with eng:
            out = np.asarray(
                eng.submit(prompt, SamplingParams(max_new_tokens=n))
                .result(timeout=120))
        np.testing.assert_array_equal(out, ref)
        assert len(out) == n
        assert eng.stats()["spec"]["verify_compiles"] == {7: 1}


# ---------------------------------------------------------------------------
# chaos: failure containment
# ---------------------------------------------------------------------------


class TestChaos:
    def test_flaky_verify_fails_only_the_victim(self):
        """``flaky@op=spec_verify`` fails the speculating request as a
        typed ``SpecDecodeError`` (stage/request/iteration attributed)
        while the co-resident SAMPLED stream completes bit-identical
        to its standalone reference."""
        model = _lm1()
        params = model.init(jax.random.PRNGKey(0))
        dm = _draft()
        dp = dm.init(jax.random.PRNGKey(1))
        sp_s = SamplingParams(max_new_tokens=12, temperature=0.7,
                              top_k=8)
        key = jax.random.PRNGKey(9)
        prompt_a = _prompts()[0]
        prompt_b = _prompts()[1]
        ref_b = _standalone(model, params, prompt_b, sp_s, key)
        eng = InferenceEngine(model, params, _spec_cfg(dm, dp,
                                                       n_slots=2))
        eng.start()
        try:
            # warm every compile so the fault lands mid-steady-state
            eng.submit(prompt_a, SamplingParams(max_new_tokens=6)) \
                .result(timeout=120)
            eng.submit(prompt_a, SamplingParams(max_new_tokens=2,
                                                temperature=0.7,
                                                top_k=8)) \
                .result(timeout=120)
            faults.install("flaky@op=spec_verify,count=1")
            ha = eng.submit(prompt_a,
                            SamplingParams(max_new_tokens=12))
            hb = eng.submit(prompt_b, sp_s, rng=key)
            out_b = np.asarray(hb.result(timeout=120))
            with pytest.raises(SpecDecodeError) as ei:
                ha.result(timeout=120)
            assert ei.value.stage == "verify"
            assert ei.value.request_id == ha.request_id
            assert ei.value.iteration is not None
            np.testing.assert_array_equal(out_b, ref_b)
        finally:
            eng.shutdown()

    def test_delay_verify_trips_victim_deadline(self):
        """A stalled verify (``delay@op=spec_verify``) is charged to
        the speculating victim's own deadline — typed
        ``RequestDeadlineExceeded`` at the next sweep, stage
        ``running``."""
        model = _lm1()
        params = model.init(jax.random.PRNGKey(0))
        dm = _draft()
        dp = dm.init(jax.random.PRNGKey(1))
        prompt = _prompts()[0]
        eng = InferenceEngine(model, params, _spec_cfg(dm, dp,
                                                       n_slots=2))
        eng.start()
        try:
            eng.submit(prompt, SamplingParams(max_new_tokens=6)) \
                .result(timeout=120)   # warm all spec compiles
            faults.install("delay@op=spec_verify,ms=600")
            h = eng.submit(prompt, SamplingParams(max_new_tokens=40,
                                                  deadline_ms=300))
            with pytest.raises(RequestDeadlineExceeded) as ei:
                h.result(timeout=120)
            assert ei.value.stage == "running"
            assert ei.value.request_id == h.request_id
        finally:
            eng.shutdown()


# ---------------------------------------------------------------------------
# per-tenant quota
# ---------------------------------------------------------------------------


class TestTenantQuota:
    def test_quota_rejects_then_releases(self, monkeypatch):
        monkeypatch.setenv("DPX_SERVE_TENANT_MAX_INFLIGHT", "1")
        model = _lm1()
        params = model.init(jax.random.PRNGKey(0))
        eng = InferenceEngine(model, params,
                              EngineConfig(n_slots=2, max_len=MAX_LEN,
                                           buckets=BUCKETS))
        prompt = _prompts()[0]
        eng.start()
        try:
            h1 = eng.submit(prompt, SamplingParams(max_new_tokens=24),
                            tenant="t0")
            with pytest.raises(AdmissionRejected) as ei:
                eng.submit(prompt, SamplingParams(max_new_tokens=4),
                           tenant="t0")
            assert ei.value.reason == "tenant_quota"
            assert ei.value.tenant == "t0"
            # a DIFFERENT tenant is not throttled by t0's quota
            h2 = eng.submit(prompt, SamplingParams(max_new_tokens=4),
                            tenant="t1")
            h1.result(timeout=120)
            h2.result(timeout=120)
            # the credit came back at retirement
            h3 = eng.submit(prompt, SamplingParams(max_new_tokens=4),
                            tenant="t0")
            assert h3.result(timeout=120).shape == (4,)
            assert h3.metrics["tenant"] == "t0"
        finally:
            eng.shutdown()


# ---------------------------------------------------------------------------
# construction-time guard rails
# ---------------------------------------------------------------------------


class TestConstruction:
    def test_spec_without_draft_raises(self):
        model = _lm1()
        params = model.init(jax.random.PRNGKey(0))
        with pytest.raises(ValueError, match="draft_model"):
            InferenceEngine(model, params,
                            EngineConfig(spec_decode=True))
        with pytest.raises(ValueError, match="draft_model"):
            DisaggEngine(model, params,
                         DisaggConfig(spec_decode=True))

    def test_draft_len_must_be_positive(self):
        from distributed_pytorch_tpu.serve.spec import (SpecConfig,
                                                        SpecState)
        model = _lm1()
        params = model.init(jax.random.PRNGKey(0))
        with pytest.raises(ValueError, match="draft_len"):
            SpecState(SpecConfig(draft_model=model,
                                 draft_params=params, draft_len=0),
                      2, MAX_LEN)
