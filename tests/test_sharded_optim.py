"""optim/sharded — cross-replica sharded weight update (ZeRO-1) on the
quantized ring (ISSUE 7): flat layout geometry, bit-exact per-slice
optimizer math vs the replicated step, the native reduce-scatter/
all-gather leg parity against the numpy wire spec, byte accounting +
error-feedback residual bounds (the PR 1 acceptance pattern), both
front doors end to end (SPMD mesh + host TCP ring), chaos kill
mid-reduce-scatter with typed op attribution, and the sharded-optimizer
checkpoint written at dp=4 restoring bit-exact at dp=2."""

import multiprocessing as mp
import os
import sys
import threading
import time

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
from jax.sharding import PartitionSpec as P  # noqa: E402

import distributed_pytorch_tpu as dist  # noqa: E402
from distributed_pytorch_tpu import models, optim  # noqa: E402
from distributed_pytorch_tpu.comm import primitives as prim  # noqa: E402
from distributed_pytorch_tpu.comm import wire  # noqa: E402
from distributed_pytorch_tpu.ops.losses import cross_entropy  # noqa: E402
from distributed_pytorch_tpu.optim.sharded import (  # noqa: E402
    ShardedOptState, build_layout, lcm_pad_multiple, shard_optimizer)
from distributed_pytorch_tpu.optim.sharded import (  # noqa: E402
    make_sharded_train_step)
from distributed_pytorch_tpu.parallel import make_train_step  # noqa: E402
from distributed_pytorch_tpu.runtime import faults  # noqa: E402
from distributed_pytorch_tpu.runtime.multiprocess import (  # noqa: E402
    launch_multiprocess)
from distributed_pytorch_tpu.runtime.watchdog import WorkerFailure  # noqa: E402

BLOCK = wire.QUANT_BLOCK


def _params():
    """A small mixed-shape/mixed-size param tree (every leaf smaller
    than one quant block, so per-leaf padding is actually exercised)."""
    rng = np.random.default_rng(0)
    return {
        "emb": {"w": jnp.asarray(rng.standard_normal((16, 8)),
                                 jnp.float32)},
        "ln": {"scale": jnp.asarray(np.ones(8), jnp.float32),
               "bias": jnp.asarray(np.zeros(8), jnp.float32)},
        "head": {"w": jnp.asarray(rng.standard_normal((8, 4)) * 0.1,
                                  jnp.float32)},
    }


def _grads_like(tree, seed=1, scale=1e-2):
    rng = np.random.default_rng(seed)
    return jax.tree_util.tree_map(
        lambda p: jnp.asarray(rng.standard_normal(np.shape(p)) * scale,
                              jnp.float32), tree)


# ---------------------------------------------------------------------------
# flat layout geometry
# ---------------------------------------------------------------------------


class TestFlatLayout:
    def test_roundtrip_and_block_alignment(self):
        params = _params()
        lay = build_layout(params, 4)
        # every leaf starts on a block edge; total pads to world*block
        for off in lay.offsets:
            assert off % BLOCK == 0
        assert lay.n_padded % (4 * BLOCK) == 0
        assert lay.seg % BLOCK == 0
        flat = lay.flatten_np(params)
        back = lay.unflatten_jnp(jnp.asarray(flat))
        for a, b in zip(jax.tree_util.tree_leaves(params),
                        jax.tree_util.tree_leaves(back)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        # jnp flatten agrees with the numpy flatten bit for bit
        np.testing.assert_array_equal(
            np.asarray(lay.flatten_jnp(params)), flat)

    def test_equal_grid_matches_ring_grid(self):
        """The equal-segment grid the SPMD psum_scatter needs IS the
        block grid the native ring computes (the tail pad makes block
        counts divide evenly), so both front doors share one ownership
        map."""
        lay = build_layout(_params(), 4)
        for rank in range(4):
            lo, cnt = wire.ring_owned_span(lay.n_padded, 4, rank)
            slo, shi = lay.span(lay.ring_segment(rank))
            assert (lo, lo + cnt) == (slo, shi)

    def test_scalar_and_python_leaves_roundtrip(self):
        """Bare Python scalars and 0-d leaves survive the flat layout
        (dtype extraction must not assume .dtype exists)."""
        tree = {"w": jnp.ones((4, 4), jnp.float32), "t": 0.5,
                "s": jnp.asarray(2.0, jnp.float32)}
        lay = build_layout(tree, 2)
        back = lay.unflatten_jnp(jnp.asarray(lay.flatten_np(tree)))
        assert float(back["t"]) == 0.5
        assert float(back["s"]) == 2.0
        np.testing.assert_array_equal(np.asarray(back["w"]),
                                      np.ones((4, 4), np.float32))

    def test_pad_multiple_makes_layouts_portable(self):
        params = _params()
        pm = lcm_pad_multiple([4, 2])
        l4 = build_layout(params, 4, pad_multiple=pm)
        l2 = build_layout(params, 2, pad_multiple=pm)
        assert l4.n_padded == l2.n_padded
        assert l4.offsets == l2.offsets
        with pytest.raises(ValueError, match="multiple"):
            build_layout(params, 4, pad_multiple=2 * BLOCK)

    def test_state_specs_shard_flat_vectors_only(self):
        params = _params()
        lay = build_layout(params, 4)
        opt = optim.adamw(1e-3)
        state = shard_optimizer(opt, lay).init_global(params)
        specs = lay.state_specs(state)
        flat_specs = jax.tree_util.tree_leaves(
            specs, is_leaf=lambda x: isinstance(x, P))
        leaves = jax.tree_util.tree_leaves(state)
        assert len(flat_specs) == len(leaves)
        for leaf, spec in zip(leaves, flat_specs):
            if np.ndim(leaf) == 1 and leaf.shape[0] == lay.n_padded:
                assert spec == P("dp")
            elif np.ndim(leaf) == 0:
                assert spec == P()


# ---------------------------------------------------------------------------
# acceptance: bit-exact per-leaf step on the owned slice (f32 AdamW)
# ---------------------------------------------------------------------------


class TestSlicedStepBitExact:
    @pytest.mark.parametrize("make_opt", [
        lambda: optim.adamw(1e-3),
        lambda: optim.sgd(1e-2, momentum=0.9),
    ], ids=["adamw", "sgd_momentum"])
    def test_sharded_update_equals_replicated_slice(self, make_opt):
        """Given the same mean gradients, the sharded optimizer's step
        on each owned slice is BIT-IDENTICAL to the replicated
        optimizer's step on the whole tree, sliced — the ISSUE 7
        numerical-equivalence acceptance criterion, over 3 steps."""
        world = 4
        params = _params()
        lay = build_layout(params, world)
        opt = make_opt()
        sharded = shard_optimizer(opt, lay)

        rep_params = params
        rep_state = opt.init(params)
        flat0 = lay.flatten_np(params)
        sl_states = [
            sharded.init_flat(jnp.asarray(
                flat0[lay.span(lay.ring_segment(r))[0]:
                      lay.span(lay.ring_segment(r))[1]]))
            for r in range(world)]

        for step_i in range(3):
            grads = _grads_like(params, seed=10 + step_i)
            rep_params, rep_state = jax.jit(opt.update)(
                grads, rep_state, rep_params)
            flat_g = lay.flatten_np(grads)
            flat_new = np.zeros_like(flat_g)
            for r in range(world):
                lo, hi = lay.span(lay.ring_segment(r))
                new_master, sl_states[r] = jax.jit(
                    sharded.update_flat)(jnp.asarray(flat_g[lo:hi]),
                                         sl_states[r])
                flat_new[lo:hi] = np.asarray(new_master)
            sh_params = lay.unflatten_jnp(jnp.asarray(flat_new))
            for a, b in zip(jax.tree_util.tree_leaves(rep_params),
                            jax.tree_util.tree_leaves(sh_params)):
                np.testing.assert_array_equal(
                    np.asarray(a), np.asarray(b))

    def test_shard_optimizer_rejects_non_optimizer(self):
        lay = build_layout(_params(), 2)
        with pytest.raises(TypeError, match="Optimizer"):
            shard_optimizer(lambda g, s, p: (p, s), lay)

    def test_adafactor_rejected_as_non_elementwise(self):
        """Silent corruption becomes a typed error: adafactor's
        factored moments cannot be updated on a flat slice — detected
        by state type at init (bare and composed)."""
        params = _params()
        lay = build_layout(params, 2)
        for opt in (optim.adafactor(1e-3),
                    optim.with_schedule(lambda lr: optim.adafactor(lr),
                                        optim.constant(1e-3))):
            with pytest.raises(TypeError, match="ELEMENTWISE"):
                shard_optimizer(opt, lay).init_global(params)


# ---------------------------------------------------------------------------
# wire: the standalone legs vs the executable spec + byte accounting
# ---------------------------------------------------------------------------


class TestWireLegSpecs:
    def _ranks(self, world, n, seed=0):
        rng = np.random.default_rng(seed)
        return [(rng.standard_normal(n) * 2).astype(np.float32)
                for _ in range(world)]

    def test_legs_compose_to_the_allreduce_bit_exactly(self):
        """reduce-scatter sim + all-gather sim == simulate_quant_ring,
        bit for bit — which is itself pinned bit-identical to the
        native dpx_allreduce_q8, so the standalone native legs share
        the same oracle."""
        for world in (2, 4, 8):
            xs = self._ranks(world, 3 * BLOCK + 123, seed=world)
            ref, ref_bytes = wire.simulate_quant_ring(xs)
            bufs, b1 = wire.simulate_quant_reduce_scatter(xs)
            outs, b2 = wire.simulate_quant_allgather(bufs)
            assert b1 + b2 == ref_bytes
            for r in range(world):
                np.testing.assert_array_equal(outs[r],
                                              ref[r].ravel())

    def test_reduce_scatter_owned_span_holds_the_sum(self):
        world, n = 4, 2 * BLOCK * 4 + 77
        xs = self._ranks(world, n, seed=3)
        bufs, _ = wire.simulate_quant_reduce_scatter(xs)
        exact = np.sum(np.stack(xs), axis=0, dtype=np.float64)
        for r in range(world):
            lo, cnt = wire.ring_owned_span(n, world, r)
            got = bufs[r][lo:lo + cnt]
            want = exact[lo:lo + cnt]
            err = np.abs(got - want).max() / (np.abs(want).max() + 1e-12)
            assert err <= 2.5e-2, (r, err)

    def test_allgather_bit_identical_across_ranks(self):
        world, n = 4, 3 * BLOCK * 4
        bufs = self._ranks(world, n, seed=5)
        outs, _ = wire.simulate_quant_allgather(bufs)
        for r in range(1, world):
            np.testing.assert_array_equal(outs[r], outs[0])

    def test_leg_byte_accounting_and_ratio(self):
        """ISSUE 7 acceptance: each leg is half the quant allreduce;
        the sharded update's two quantized legs move >= 3.5x fewer
        bytes than the f32 replicated ring's allreduce."""
        n = 1 << 20
        for world in (2, 4, 8):
            leg = wire.quant_leg_wire_bytes(n, world)
            assert 2 * leg == wire.quant_ring_allreduce_wire_bytes(
                n, world)
            ratio = wire.ring_allreduce_wire_bytes(n, world) / (2 * leg)
            assert ratio >= 3.5, (world, ratio)
        assert wire.quant_leg_wire_bytes(n, 1) == 0

    def test_sim_bytes_match_accounting(self):
        world, n = 4, 5 * BLOCK + 9
        xs = self._ranks(world, n)
        _, rs_bytes = wire.simulate_quant_reduce_scatter(xs)
        assert rs_bytes == wire.quant_leg_wire_bytes(n, world)


# ---------------------------------------------------------------------------
# error feedback: the gather-leg residual (PR 1 acceptance pattern)
# ---------------------------------------------------------------------------


class TestParamResidual:
    def test_master_to_grid_gap_bounded_and_not_compounding(self):
        """The sharded state's exact master vs the broadcast int8-grid
        params: the gap stays within HALF a quantization step per block
        on EVERY step (it re-derives from the fresh master instead of
        accumulating) — the error-feedback property of the gather leg."""
        params = _params()
        lay = build_layout(params, 1)
        opt = optim.adamw(1e-3)
        sharded = shard_optimizer(opt, lay)
        state = sharded.init_global(params)
        upd = jax.jit(sharded.update_flat)
        g = jnp.asarray(lay.flatten_np(_grads_like(params, seed=2)))
        for step_i in range(50):
            new_master, state = upd(g, state)
            master = np.asarray(new_master)
            q, s = wire.quantize_blocks(master)
            working = wire.dequantize_blocks(q, s)
            for b in range(s.size):
                blk = slice(b * BLOCK, (b + 1) * BLOCK)
                gap = np.abs(working[blk] - master[blk]).max()
                assert gap <= s[b] / 2 + 1e-7, (step_i, b, gap)

    def test_grad_leg_reuses_pr1_error_feedback(self):
        """The host engine's scatter leg carries the PR 1
        ErrorFeedback residual: time-averaged transmitted gradients
        converge to the true gradient (re-asserted here over the
        sharded bucket layout, with the per-leaf padding in place)."""
        from distributed_pytorch_tpu.ops.quant import ErrorFeedback
        params = _params()
        lay = build_layout(params, 4)
        g = lay.flatten_np(_grads_like(params, seed=3, scale=1e-3))
        ef = ErrorFeedback()
        outs = [ef.compensate(g) for _ in range(64)]
        single = np.abs(outs[0] - g).max()
        averaged = np.abs(np.mean(outs, axis=0) - g).max()
        assert averaged < single / 10
        q, s = wire.quantize_blocks(g)
        assert np.abs(ef.residual).max() <= s.max()


# ---------------------------------------------------------------------------
# SPMD front door (8-device virtual mesh)
# ---------------------------------------------------------------------------


class TestSpmdSharded:
    """The SPMD sharded-vs-replicated trajectory matrix moved to the
    spec-driven suite (tests/test_front_door.py::TestSpecMatrix — the
    ISSUE 13 collapse); this class keeps only what is NOT a per-front-
    door duplicate: the checkpoint-facing spec exports and validation."""

    def _setup(self):
        model = models.DummyModel(in_dim=1, hidden_dim=32, n_classes=4)
        params = model.init(jax.random.PRNGKey(0))
        opt = optim.adamw(1e-3)

        def loss_fn(p, batch):
            x, y = batch
            return cross_entropy(model.apply(p, x), y), {}

        x = dist.shard_batch(np.arange(16, dtype=np.float32)[:, None])
        y = dist.shard_batch((np.arange(16) % 4).astype(np.int32))
        return params, opt, loss_fn, (x, y)

    def test_init_opt_state_is_sharded_state(self, group8):
        params, opt, loss_fn, batch = self._setup()
        step = make_train_step(loss_fn, opt, donate=False,
                               weight_update="sharded")
        assert isinstance(step.init_opt_state(params), ShardedOptState)

    def test_state_specs_exported_for_ckpt(self, group8):
        params, opt, loss_fn, batch = self._setup()
        step = make_train_step(loss_fn, opt, donate=False,
                               weight_update="sharded")
        state = step.init_opt_state(params)
        specs = step.state_specs(state)
        assert specs.master == P("dp")
        assert specs.inner.mu == P("dp")
        assert specs.inner.step == P()

    def test_weight_update_validated_and_env_default(self, group8,
                                                     monkeypatch):
        params, opt, loss_fn, batch = self._setup()
        with pytest.raises(ValueError, match="weight_update"):
            make_train_step(loss_fn, opt, weight_update="zero9")
        monkeypatch.setenv("DPX_WEIGHT_UPDATE", "sharded")
        step = make_train_step(loss_fn, opt, donate=False)
        assert hasattr(step, "init_opt_state")

    def test_world1_same_state_structure(self):
        """At world==1 the sharded step runs unsharded but keeps the
        global flat state structure — checkpoints stay portable."""
        model = models.DummyModel(in_dim=1, hidden_dim=32, n_classes=4)
        params = model.init(jax.random.PRNGKey(0))
        opt = optim.adamw(1e-3)

        def loss_fn(p, batch):
            x, y = batch
            return cross_entropy(model.apply(p, x), y), {}

        step = make_train_step(loss_fn, opt, donate=False,
                               weight_update="sharded")
        state = step.init_opt_state(params)
        assert isinstance(state, ShardedOptState)
        x = np.arange(8, dtype=np.float32)[:, None]
        y = (np.arange(8) % 4).astype(np.int32)
        out = step(params, state, (x, y))
        assert np.isfinite(float(out.loss.mean()))


class TestQuantizedLegPrimitives:
    def test_quantized_reduce_scatter_sums(self, group8):
        from distributed_pytorch_tpu.runtime.jax_compat import shard_map
        mesh = dist.get_mesh()
        n = 8 * 2 * BLOCK
        xs = np.stack([(np.random.default_rng(r).standard_normal(n))
                       .astype(np.float32) for r in range(8)])

        def island(x):
            return prim.quantized_reduce_scatter(x[0], "dp")[None]

        f = shard_map(island, mesh=mesh, in_specs=(P("dp"),),
                      out_specs=P("dp"), check_vma=False)
        out = np.asarray(jax.jit(f)(jnp.asarray(xs))).ravel()
        exact = xs.sum(axis=0, dtype=np.float64)
        err = np.abs(out - exact).max() / np.abs(exact).max()
        assert err <= 1e-2, err

    def test_quantized_all_gather_bit_identical(self, group8):
        from distributed_pytorch_tpu.runtime.jax_compat import shard_map
        mesh = dist.get_mesh()
        chunk = 2 * BLOCK
        xs = np.stack([(np.random.default_rng(r).standard_normal(chunk))
                       .astype(np.float32) for r in range(8)])

        def island(x):
            return prim.quantized_all_gather(x[0], "dp")[None]

        f = shard_map(island, mesh=mesh, in_specs=(P("dp"),),
                      out_specs=P("dp"), check_vma=False)
        out = np.asarray(jax.jit(f)(jnp.asarray(xs)))
        # every device decoded the same bytes — replicated values
        # rebuilt from sharded updates cannot drift
        for r in range(1, 8):
            np.testing.assert_array_equal(out[r], out[0])
        # within one quantization step of the exact concatenation
        # (NOT asserted bit-equal to the numpy codec: XLA lowers the
        # /127 to a reciprocal multiply, a 1-ulp scale difference)
        flat = xs.ravel()
        _, s = wire.quantize_blocks(flat)
        per_elem = np.repeat(s, BLOCK)[:flat.size]
        assert np.all(np.abs(out[0] - flat) <= per_elem / 2 + 1e-6)

    def test_divisibility_validated(self, group8):
        from distributed_pytorch_tpu.runtime.jax_compat import shard_map
        mesh = dist.get_mesh()
        bad = np.zeros((8, 10), np.float32)
        for fn in (prim.quantized_reduce_scatter,
                   prim.quantized_all_gather):
            island = lambda x: fn(x[0], "dp")[None]  # noqa: B023
            f = shard_map(island, mesh=mesh, in_specs=(P("dp"),),
                          out_specs=P("dp"), check_vma=False)
            with pytest.raises(ValueError, match="divisible"):
                f(jnp.asarray(bad))


# ---------------------------------------------------------------------------
# host front door (native TCP ring, spawned processes)
# ---------------------------------------------------------------------------


class TestHostSharded:
    """The world-2 host sharded/quant trajectory + CommStats twins
    moved to the spec-driven suite (tests/test_front_door.py::
    TestHostMatrix — the ISSUE 13 collapse). What stays is the native
    leg bit-parity against the numpy wire spec, which no other door
    exercises."""

    @pytest.mark.slow
    def test_world4_sharded_native_legs_match_numpy_spec(self):
        """Native dpx_reduce_scatter_q8 / dpx_allgather_q8 vs the wire
        spec sims: owned spans and gathered buffers bit-identical."""
        ctx = mp.get_context("spawn")
        q = ctx.Queue()
        n = 70000
        launch_multiprocess(_native_leg_worker, 4, q, n)
        res = {}
        while len(res) < 4:
            rank, rs_hex, ag_hex = q.get(timeout=120)
            res[rank] = (rs_hex, ag_hex)
        import hashlib
        xs = [(np.random.default_rng(100 + r).standard_normal(n) * 2)
              .astype(np.float32) for r in range(4)]
        bufs, _ = wire.simulate_quant_reduce_scatter(xs)
        outs, _ = wire.simulate_quant_allgather(bufs)
        for r in range(4):
            lo, cnt = wire.ring_owned_span(n, 4, r)
            want_rs = hashlib.sha256(
                np.ascontiguousarray(bufs[r][lo:lo + cnt]).tobytes()
            ).hexdigest()
            want_ag = hashlib.sha256(
                np.ascontiguousarray(outs[r]).tobytes()).hexdigest()
            assert res[r] == (want_rs, want_ag), r


def _native_leg_worker(rank, world, q, n):
    import hashlib

    import numpy as _np

    import distributed_pytorch_tpu as _dist
    from distributed_pytorch_tpu.comm import wire as _wire
    from distributed_pytorch_tpu.runtime import context as _ctx

    _dist.init_process_group(rank, world)
    try:
        comm = _ctx.get_host_comm()
        x = (_np.random.default_rng(100 + rank).standard_normal(n) * 2
             ).astype(_np.float32)
        buf = x.copy()
        comm.reduce_scatter_q8(buf)
        lo, cnt = _wire.ring_owned_span(n, world, rank)
        rs_hex = hashlib.sha256(
            _np.ascontiguousarray(buf[lo:lo + cnt]).tobytes()).hexdigest()
        # feed the SAME post-reduce-scatter buffer to the gather leg —
        # exactly the sharded update's dataflow (sans the local step)
        comm.allgather_q8(buf)
        ag_hex = hashlib.sha256(
            _np.ascontiguousarray(buf).tobytes()).hexdigest()
        q.put((rank, rs_hex, ag_hex))
    finally:
        _dist.cleanup()


# ---------------------------------------------------------------------------
# chaos: kill mid-reduce-scatter (DPX_FAULT grammar, typed attribution)
# ---------------------------------------------------------------------------

CHAOS_TIMEOUT_MS = 2000


def _report_and_reraise(q, rank, fn):
    from distributed_pytorch_tpu.runtime.native import CommError
    t0 = time.monotonic()
    try:
        fn()
    except CommError as e:
        q.put((rank, type(e).__name__, e.op, e.peer,
               time.monotonic() - t0))
        q.close()
        q.join_thread()
        raise
    q.put((rank, None, None, None, time.monotonic() - t0))


def _sharded_chaos_worker(rank, world, q):
    """Two clean sharded-update comm cycles, then rank 2 is killed
    entering its third reduce_scatter (mid-leg for everyone else)."""
    import numpy as _np

    import distributed_pytorch_tpu as _dist
    from distributed_pytorch_tpu.runtime import context as _ctx

    _dist.init_process_group(rank, world)
    comm = _ctx.get_host_comm()
    buf = _np.ones(8 * 1024, _np.float32)
    for _ in range(2):
        comm.reduce_scatter_q8(buf.copy())
        comm.allgather_q8(buf.copy())
    _report_and_reraise(
        q, rank, lambda: comm.reduce_scatter_q8(buf.copy()))


def test_chaos_kill_mid_reduce_scatter_world4(monkeypatch):
    """ISSUE 7 satellite: the reduce_scatter/allgather ops are live in
    the DPX_FAULT grammar — a rank killed mid-reduce-scatter in a world
    of 4 surfaces as typed CommErrors on every survivor, attributed to
    op "reduce_scatter", within the deadline bound (no hang)."""
    assert "reduce_scatter" in faults.COMM_OPS
    assert "allgather" in faults.COMM_OPS
    (spec,) = faults.parse_fault_spec("kill@op=reduce_scatter,call=3,rank=2")
    assert spec.action == "kill" and spec.op == "reduce_scatter"

    monkeypatch.setenv(faults.FAULT_ENV,
                       "kill@op=reduce_scatter,call=3,rank=2")
    monkeypatch.setenv("DPX_COMM_TIMEOUT_MS", str(CHAOS_TIMEOUT_MS))
    ctx = mp.get_context("spawn")
    q = ctx.Queue()
    result = {}

    def run():
        try:
            launch_multiprocess(_sharded_chaos_worker, 4, q)
        except BaseException as e:  # noqa: BLE001
            result["exc"] = e

    t = threading.Thread(target=run, name="test-sharded-chaos",
                         daemon=True)
    t.start()
    t.join(timeout=120)
    assert not t.is_alive(), "chaos run hung: deadline guard failed"
    assert isinstance(result.get("exc"), WorkerFailure)
    failure = result["exc"]
    assert failure.rank == 2
    assert failure.op == "reduce_scatter"
    assert failure.exitcode == faults.KILL_EXIT_CODE

    reports = {}
    while len(reports) < 3:
        rank, kind, op, peer, elapsed = q.get(timeout=10)
        reports[rank] = (kind, op, peer, elapsed)
    assert set(reports) == {0, 1, 3}
    for rank, (kind, op, peer, elapsed) in reports.items():
        assert kind in ("CommPeerDied", "CommTimeout"), (rank, kind)
        assert op == "reduce_scatter"
        assert elapsed < 2 * CHAOS_TIMEOUT_MS / 1000.0, (rank, elapsed)


# ---------------------------------------------------------------------------
# ckpt: sharded-optimizer checkpoint written at dp=4 restores at dp=2
# ---------------------------------------------------------------------------


class TestShardedOptCkptReshard:
    CUT, TOTAL = 2, 4

    def _setup(self, world):
        dist.init_process_group(rank=0, world_size=world)
        model = models.DummyModel(in_dim=1, hidden_dim=32, n_classes=4)
        params = model.init(jax.random.PRNGKey(0))
        opt = optim.adamw(1e-2)

        def loss_fn(p, batch):
            x, y = batch
            return cross_entropy(model.apply(p, x), y), {}

        step = make_sharded_train_step(
            loss_fn, opt, donate=False,
            pad_multiple=lcm_pad_multiple([4, 2]))
        return params, step

    def _batches(self):
        rng = np.random.default_rng(7)
        return [(rng.random((8, 1), dtype=np.float32),
                 rng.integers(0, 4, (8,)).astype(np.int32))
                for _ in range(self.TOTAL)]

    def _shard_batch(self, b):
        return tuple(dist.shard_batch(v) for v in b)

    def test_dp4_ckpt_restores_bit_exact_at_dp2(self, tmp_path):
        from distributed_pytorch_tpu.ckpt import CheckpointManager
        from distributed_pytorch_tpu.parallel.tensor import (
            replicated_specs, shard_params)

        # uninterrupted dp=4 reference trajectory
        params, step = self._setup(4)
        st = step.init_opt_state(params)
        ref_losses, p, s = [], params, st
        for b in self._batches():
            out = step(p, s, self._shard_batch(b))
            p, s = out.params, out.opt_state
            ref_losses.append(float(out.loss.mean()))
        dist.cleanup()

        # dp=4 run, checkpointing the sharded state at step CUT
        params, step = self._setup(4)
        st = step.init_opt_state(params)
        p, s = params, st
        mgr = CheckpointManager(
            str(tmp_path), sharded=True,
            param_specs=replicated_specs(params),
            opt_specs=step.state_specs(st), axis_sizes={"dp": 4})
        for i, b in enumerate(self._batches()[:self.CUT]):
            out = step(p, s, self._shard_batch(b))
            p, s = out.params, out.opt_state
            mgr.save(i + 1, p, s, force=(i + 1 == self.CUT))
        mgr.wait()
        saved_state = jax.tree_util.tree_map(np.asarray, s)
        dist.cleanup()

        # restore at dp=2: same global flat length (lcm pad_multiple),
        # so the resharding reader re-slices the moments for free
        from distributed_pytorch_tpu.utils.checkpoint import (
            restore_checkpoint)
        params2, step2 = self._setup(2)
        template = step2.init_opt_state(params2)
        ck = restore_checkpoint(str(tmp_path), like_params=params2,
                                like_opt_state=template)
        assert ck.step == self.CUT
        # bit-exact: the dp=2 restore holds exactly the dp=4 moments
        for a, b in zip(jax.tree_util.tree_leaves(saved_state),
                        jax.tree_util.tree_leaves(ck.opt_state)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        # and the run continues loss-correctly on the shrunk world
        from distributed_pytorch_tpu.runtime import context
        p2 = ck.params
        s2 = shard_params(ck.opt_state, step2.state_specs(template),
                          context.get_mesh())
        for i, b in enumerate(self._batches()[self.CUT:]):
            out = step2(p2, s2, self._shard_batch(b))
            p2, s2 = out.params, out.opt_state
            np.testing.assert_allclose(
                float(out.loss.mean()), ref_losses[self.CUT + i],
                rtol=1e-4, atol=1e-5)
        dist.cleanup()
