"""dpxverify tests (ISSUE 20): the SPMD collective-order rules
(DPX009-011) on minimal bad/good fixtures, the interprocedural call
graph, the repo-clean gate, and the runtime collective sanitizer — an
injected skipped-collective divergence at world 4 must raise a typed
``CollectiveMismatch`` within one fingerprint exchange, not one
``DPX_COMM_TIMEOUT_MS`` deadline."""

import multiprocessing as mp
import os
import sys
import textwrap
import threading
import time

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from distributed_pytorch_tpu.analysis import spmd
from distributed_pytorch_tpu.analysis.lint import (apply_baseline,
                                                   load_baseline,
                                                   save_baseline)
from distributed_pytorch_tpu.comm.sanitizer import (RECORD_SIZE,
                                                    CollectiveMismatch,
                                                    CollectiveSanitizer)
from distributed_pytorch_tpu.runtime import faults
from distributed_pytorch_tpu.runtime.multiprocess import launch_multiprocess
from distributed_pytorch_tpu.runtime.native import CommError, HostComm
from distributed_pytorch_tpu.runtime.watchdog import WorkerFailure

TIMEOUT_MS = 60_000  # deliberately HUGE: the sanitizer must beat it


def _verify_snippet(tmp_path, source, rel="distributed_pytorch_tpu/mod.py"):
    """Verify one fixture file at a package-relative path (the SPMD
    rules are package-scoped; tests/ stage divergence legitimately)."""
    path = tmp_path / rel
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(textwrap.dedent(source))
    return spmd.verify_paths(None, root=str(tmp_path))


def _rules(findings):
    return [f.rule for f in findings]


# ---------------------------------------------------------------------------
# DPX009 — collective on one side of a rank-divergent branch
# ---------------------------------------------------------------------------


class TestDPX009:
    def test_one_sided_collective_flagged_at_site(self, tmp_path):
        bad = """
            def step(comm, rank):
                if rank == 0:
                    comm.barrier()
                comm.allreduce(x)
        """
        findings = _verify_snippet(tmp_path, bad)
        assert _rules(findings) == ["DPX009"]
        assert findings[0].line == 4          # the barrier call itself
        assert "barrier" in findings[0].message

    def test_guard_clause_implicit_else(self, tmp_path):
        # `if rank != 0: return` then barrier: only rank 0 barriers
        bad = """
            def save(comm, rank):
                if rank != 0:
                    return
                comm.barrier()
        """
        findings = _verify_snippet(tmp_path, bad)
        assert _rules(findings) == ["DPX009"]
        assert findings[0].line == 5

    def test_is_primary_spelling(self, tmp_path):
        bad = """
            def commit(comm):
                if is_primary():
                    comm.barrier()
        """
        assert _rules(_verify_snippet(tmp_path, bad)) == ["DPX009"]

    def test_balanced_arms_clean(self, tmp_path):
        good = """
            def step(comm, rank):
                if rank == 0:
                    comm.barrier()
                else:
                    comm.barrier()
        """
        assert _verify_snippet(tmp_path, good) == []

    def test_data_dependent_branch_clean(self, tmp_path):
        good = """
            def step(comm, loss):
                if loss > 10.0:
                    log(loss)
                comm.barrier()
        """
        assert _verify_snippet(tmp_path, good) == []

    def test_interprocedural_effect(self, tmp_path):
        # the collective hides one call deep; flagged at the CALL site
        bad = """
            def _sync(comm):
                comm.barrier()

            def step(comm, rank):
                if rank == 0:
                    _sync(comm)
        """
        findings = _verify_snippet(tmp_path, bad)
        assert _rules(findings) == ["DPX009"]
        assert findings[0].line == 7
        assert "barrier" in findings[0].message

    def test_cross_module_effect(self, tmp_path):
        (tmp_path / "distributed_pytorch_tpu").mkdir(parents=True,
                                                     exist_ok=True)
        (tmp_path / "distributed_pytorch_tpu" / "helpers.py").write_text(
            textwrap.dedent("""
                def flush_world(comm):
                    comm.barrier()
            """))
        bad = """
            def step(comm, rank):
                if rank == 0:
                    flush_world(comm)
        """
        findings = _verify_snippet(tmp_path, bad)
        assert _rules(findings) == ["DPX009"]

    def test_suppression_marker(self, tmp_path):
        waived = """
            def step(comm, rank):
                if rank == 0:
                    # dpxlint: disable=DPX009 rooted save, peers wait at the outer barrier
                    comm.barrier()
        """
        assert _verify_snippet(tmp_path, waived) == []


# ---------------------------------------------------------------------------
# DPX010 — early exit skipping the second of a paired sequence
# ---------------------------------------------------------------------------


class TestDPX010:
    def test_rank_dependent_early_return(self, tmp_path):
        bad = """
            def train(comm, rank, bad):
                comm.barrier()
                if rank == 0 and bad:
                    return None
                comm.allreduce(x)
        """
        findings = _verify_snippet(tmp_path, bad)
        assert "DPX010" in _rules(findings)
        ret = next(f for f in findings if f.rule == "DPX010")
        assert ret.line == 5                  # the return statement

    def test_swallowing_except_around_collective(self, tmp_path):
        bad = """
            def sync(comm):
                comm.barrier()
                try:
                    work()
                    comm.allreduce(x)
                except Exception:
                    log()
        """
        findings = _verify_snippet(tmp_path, bad)
        assert _rules(findings) == ["DPX010"]
        assert findings[0].line == 7          # the except handler
        assert "allreduce" in findings[0].message

    def test_reraising_handler_clean(self, tmp_path):
        good = """
            def sync(comm):
                comm.barrier()
                try:
                    comm.allreduce(x)
                except Exception:
                    log()
                    raise
        """
        assert _verify_snippet(tmp_path, good) == []

    def test_always_raising_helper_clean(self, tmp_path):
        # the HierRing._reraise shape: the handler delegates to a local
        # helper that definitely raises
        good = """
            def _reraise(op, e):
                if op == "x":
                    raise ValueError(op)
                raise RuntimeError(op)

            def sync(comm):
                comm.barrier()
                try:
                    comm.allreduce(x)
                except Exception as e:
                    _reraise("allreduce", e)
        """
        assert _verify_snippet(tmp_path, good) == []

    def test_unconditional_return_clean(self, tmp_path):
        # a rank-INDEPENDENT early return is symmetric — every rank
        # takes it or none does
        good = """
            def step(comm, n):
                comm.barrier()
                if n == 0:
                    return None
                comm.allreduce(x)
        """
        assert _verify_snippet(tmp_path, good) == []


# ---------------------------------------------------------------------------
# DPX011 — lock held across a collective
# ---------------------------------------------------------------------------


class TestDPX011:
    def test_with_lock_around_collective(self, tmp_path):
        bad = """
            class A:
                def flush(self, comm):
                    with self._lock:
                        comm.barrier()
        """
        findings = _verify_snippet(tmp_path, bad)
        assert _rules(findings) == ["DPX011"]
        assert findings[0].line == 5
        assert "self._lock" in findings[0].message

    def test_acquire_release_bracketing(self, tmp_path):
        bad = """
            def flush(comm, lock):
                lock.acquire()
                comm.barrier()
                lock.release()
        """
        findings = _verify_snippet(tmp_path, bad)
        assert _rules(findings) == ["DPX011"]
        assert findings[0].line == 4

    def test_lock_released_before_collective_clean(self, tmp_path):
        good = """
            def flush(comm, self):
                with self._lock:
                    n = compute()
                comm.barrier()
        """
        assert _verify_snippet(tmp_path, good) == []

    def test_non_lock_context_clean(self, tmp_path):
        good = """
            def save(comm, path):
                with open(path) as f:
                    f.read()
                comm.barrier()
        """
        assert _verify_snippet(tmp_path, good) == []


# ---------------------------------------------------------------------------
# repo gate + baseline machinery
# ---------------------------------------------------------------------------


def test_repo_is_clean_under_committed_baseline():
    """THE acceptance gate: `python -m tools.dpxverify` exits 0 on this
    repo — zero findings outside the committed baseline (which is
    EMPTY: the one deliberate divergence source, runtime/faults.py, is
    exempted in analysis/spmd.py with its reason)."""
    from tools.dpxverify import main
    assert main([]) == 0


def test_faults_layer_is_exempt_not_baselined():
    # the exemption is explicit and reasoned in analysis/spmd.py — a
    # rename would silently re-expose 20+ cascaded findings
    assert "distributed_pytorch_tpu/runtime/faults.py" in spmd.EXEMPT_FILES
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    assert os.path.exists(os.path.join(
        root, "distributed_pytorch_tpu", "runtime", "faults.py"))


def test_baseline_absorbs_spmd_findings(tmp_path):
    bad = """
        def step(comm, rank):
            if rank == 0:
                comm.barrier()
    """
    findings = _verify_snippet(tmp_path, bad)
    assert len(findings) == 1
    bl = tmp_path / "baseline.json"
    save_baseline(str(bl), findings)
    assert apply_baseline(findings, load_baseline(str(bl))) == []


def test_cli_format_json_and_exit2_on_unparseable(tmp_path, capsys):
    """dpxverify carries dpxlint's CLI contract: exit 2 on DPX000, and
    --format json/github for machine consumers (CI annotations)."""
    import json

    from tools.dpxverify import main
    broken = tmp_path / "broken.py"
    broken.write_text("def f(:\n")
    assert main(["--format", "json", str(broken)]) == 2
    entries = json.loads(capsys.readouterr().out)
    assert [e["rule"] for e in entries] == ["DPX000"]
    assert main(["--format", "github", str(broken)]) == 2
    out = capsys.readouterr().out
    assert out.startswith("::error file=") and "title=DPX000::" in out


def test_dpx000_syntax_error_reported(tmp_path):
    path = tmp_path / "distributed_pytorch_tpu" / "broken.py"
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text("def f(:\n")
    findings = spmd.verify_paths(None, root=str(tmp_path))
    assert _rules(findings) == ["DPX000"]


# ---------------------------------------------------------------------------
# sanitizer: wire format + unarmed structural guarantees
# ---------------------------------------------------------------------------


def test_fingerprint_roundtrip():
    class _FakeComm:
        world = 1
        rank = 0

    s = CollectiveSanitizer(_FakeComm())
    s._seq = 41
    rec = s._pack("allreduce", "float32", 2048, "train.py:123")
    assert len(rec) == RECORD_SIZE == 88
    got = s._unpack(rec)
    assert got["op"] == "allreduce" and got["dtype"] == "float32"
    assert got["seq"] == 41 and got["nbytes"] == 2048
    assert got["site"] == "train.py:123"


def test_world1_check_short_circuits():
    class _FakeComm:
        world = 1
        rank = 0
        # no _lib/_h: touching the native layer would AttributeError

    CollectiveSanitizer(_FakeComm()).check("allreduce", "float32", 8)


def test_unarmed_comm_has_no_sanitizer_and_no_overhead(monkeypatch):
    """DPX_COMM_SANITIZE unset: the feature is one `is None` test in
    _pre_op — structurally zero extra work, bounded by a loose wall
    clock (plumbing check, not a benchmark)."""
    monkeypatch.delenv("DPX_COMM_SANITIZE", raising=False)
    from distributed_pytorch_tpu.runtime.launcher import find_free_port
    comm = HostComm("127.0.0.1", find_free_port(), rank=0, world=1)
    try:
        assert comm._sanitizer is None
        t0 = time.perf_counter()
        for _ in range(300):
            comm.barrier()
        assert time.perf_counter() - t0 < 2.0
    finally:
        comm.close()


def test_armed_world1_comm_builds_sanitizer(monkeypatch):
    monkeypatch.setenv("DPX_COMM_SANITIZE", "1")
    from distributed_pytorch_tpu.runtime.launcher import find_free_port
    comm = HostComm("127.0.0.1", find_free_port(), rank=0, world=1)
    try:
        assert isinstance(comm._sanitizer, CollectiveSanitizer)
        comm.barrier()   # world-1 check short-circuits; still green
    finally:
        comm.close()


def test_collective_mismatch_is_typed_comm_error():
    e = CollectiveMismatch("divergence", op="allreduce", rank=1, peer=2,
                           seq=3, peer_op="barrier",
                           call_site="a.py:1", peer_call_site="b.py:2")
    assert isinstance(e, CommError)
    assert (e.op, e.rank, e.peer, e.seq) == ("allreduce", 1, 2, 3)
    assert e.peer_op == "barrier"


# ---------------------------------------------------------------------------
# sanitizer: world-4 multiprocess legs (the CI sanitizer smoke: -k world4)
# ---------------------------------------------------------------------------


def _report_mismatch(q, rank, fn):
    t0 = time.monotonic()
    try:
        fn()
    except CommError as e:
        q.put((rank, type(e).__name__, e.op, e.peer,
               getattr(e, "seq", None), str(e),
               time.monotonic() - t0))
        q.close()
        q.join_thread()
        raise
    q.put((rank, None, None, None, None, "", time.monotonic() - t0))


def _san_diverge_worker(rank, world, q):
    """Two clean sanitized allreduces; entering the third, rank 2's
    injected ``diverge`` issues a barrier where ranks 0,1,3 issue
    allreduce #3 — the sanitizer's fingerprint exchange must convert
    the would-be 60s timeout hang into an immediate typed
    CollectiveMismatch on EVERY rank."""
    import numpy as np
    import distributed_pytorch_tpu as dist

    dist.init_process_group(rank, world)
    for _ in range(2):
        dist.all_reduce(np.ones(512, np.float32))
    _report_mismatch(
        q, rank, lambda: dist.all_reduce(np.ones(512, np.float32)))


def test_sanitizer_catches_divergence_world4(monkeypatch):
    """Acceptance (ISSUE 20): with DPX_COMM_SANITIZE=1 an injected
    skipped-collective divergence at world 4 raises a typed
    CollectiveMismatch naming both ranks, ops, the seq no and call
    sites — within ONE fingerprint exchange, far under the (deliberately
    huge) 60s DPX_COMM_TIMEOUT_MS deadline."""
    monkeypatch.setenv("DPX_COMM_SANITIZE", "1")
    monkeypatch.setenv(faults.FAULT_ENV,
                       "diverge@op=allreduce,call=3,rank=2")
    monkeypatch.setenv("DPX_COMM_TIMEOUT_MS", str(TIMEOUT_MS))
    ctx = mp.get_context("spawn")
    q = ctx.Queue()

    result = {}

    def run():
        try:
            launch_multiprocess(_san_diverge_worker, 4, q)
        except BaseException as e:  # noqa: BLE001
            result["exc"] = e

    t = threading.Thread(target=run, name="test-sanitize-run", daemon=True)
    t.start()
    t.join(timeout=120)
    assert not t.is_alive(), "sanitized diverge run hung"
    assert isinstance(result.get("exc"), WorkerFailure)

    reports = {}
    while len(reports) < 4:
        rank, kind, op, peer, seq, msg, elapsed = q.get(timeout=10)
        reports[rank] = (kind, op, peer, seq, msg, elapsed)
    for rank, (kind, op, peer, seq, msg, elapsed) in reports.items():
        assert kind == "CollectiveMismatch", (rank, kind, msg)
        # ONE exchange, not one deadline: seconds, nowhere near 60s
        assert elapsed < 20.0, (rank, elapsed)
        assert seq == 3, (rank, seq)
        assert ".py:" in msg                  # call sites named
        assert "rank" in msg and "seq 3" in msg
    # a healthy rank names the diverging peer's op (the barrier nobody
    # else issued) and the peer rank; the victim names the reverse
    kind, op, peer, seq, msg, _ = reports[0]
    assert op == "allreduce" and peer == 2
    assert "'barrier'" in msg and "rank 2" in msg
    kind2, op2, peer2, _, msg2, _ = reports[2]
    assert op2 == "barrier" and "'allreduce'" in msg2


def _san_clean_worker(rank, world, q):
    """Sanitize a mixed collective schedule — every fingerprint
    exchange must agree and the run must exit green."""
    import numpy as np
    import distributed_pytorch_tpu as dist

    dist.init_process_group(rank, world)
    dist.all_reduce(np.ones(256, np.float32))
    dist.barrier()
    dist.broadcast(np.arange(8, dtype=np.float32))
    dist.all_gather(np.full(4, rank, np.float32))
    dist.all_reduce(np.ones(16, np.float64))
    q.put((rank, "ok"))
    q.close()
    q.join_thread()


def test_sanitizer_clean_run_world4(monkeypatch):
    """The CI smoke's green half: DPX_COMM_SANITIZE=1 over a world-4
    mixed-op run — zero mismatch findings, clean exit."""
    monkeypatch.setenv("DPX_COMM_SANITIZE", "1")
    monkeypatch.delenv(faults.FAULT_ENV, raising=False)
    monkeypatch.setenv("DPX_COMM_TIMEOUT_MS", str(TIMEOUT_MS))
    ctx = mp.get_context("spawn")
    q = ctx.Queue()
    launch_multiprocess(_san_clean_worker, 4, q)
    reports = {}
    while len(reports) < 4:
        rank, status = q.get(timeout=10)
        reports[rank] = status
    assert all(s == "ok" for s in reports.values())
