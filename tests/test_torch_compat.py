"""The torch-compat front door: the literal reference workload runs
unmodified, and its numerics match both torch's own DDP and this
framework's JAX DP engine.

Covers the round-1 gaps (VERDICT.md "What's missing" 1 and 3):

- ``/root/reference/min_DDP.py`` (binding ``import distributed as dist``
  at min_DDP.py:7) executes byte-for-byte against
  ``torch_compat/distributed.py`` — multi-process, native C++ transport,
  grad-hook DDP — with the reference's observable behavior: rank-strided
  shards, gathered world*B predictions, the SUM-not-avg loss quirk
  (min_DDP.py:122).
- Cross-implementation loss parity: the same seeded weights and batches
  produce the same loss trajectory under (a) the shim's grad-hook DDP at
  world=2, (b) torch.distributed's real gloo DDP at world=2, and (c) this
  framework's JAX DummyModel with torch-exported weights.

These tests spawn real OS processes (no JAX in the children); they skip
on platforms without the native toolchain.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest
import torch
import torch.nn as nn

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SHIM_DIR = os.path.join(REPO, "torch_compat")
REFERENCE = "/root/reference/min_DDP.py"

pytestmark = pytest.mark.skipif(
    not os.path.exists(REFERENCE), reason="reference checkout not present")


def _run_reference(world: int, *extra_args: str):
    env = dict(os.environ)
    env["PYTHONPATH"] = SHIM_DIR
    env["DPX_VISIBLE_DEVICES"] = ",".join(str(i) for i in range(world))
    env.pop("CUDA_VISIBLE_DEVICES", None)
    # -P keeps the script's own directory off sys.path so `import
    # distributed` resolves to the shim, not to /root/reference/distributed.py
    return subprocess.run(
        [sys.executable, "-P", REFERENCE, *extra_args],
        capture_output=True, text=True, timeout=300, env=env)


class TestReferenceWorkloadUnmodified:
    @pytest.mark.slow
    def test_world2_runs_and_aggregates(self):
        r = _run_reference(2, "--epochs", "1")
        assert r.returncode == 0, r.stderr[-2000:]
        out = r.stdout
        # config echoed once (print_primary)
        assert out.count("epochs      : 1") == 1
        # rank-strided, unshuffled shards (DistributedSampler contract):
        # rank 0 gets even indices, rank 1 odd
        assert "tensor([ 0,  2,  4,  6,  8, 10, 12, 14]" in out
        assert "tensor([ 1,  3,  5,  7,  9, 11, 13, 15]" in out
        # 32 samples / 2 ranks / batch 8 = 2 iterations, each aggregating
        # world*B = 16 gathered predictions on the primary
        assert out.count("Finish iteration") == 2
        assert "/16)" in out

    def test_world1_single_process(self):
        env_spec = {"DPX_VISIBLE_DEVICES": "0"}
        env = dict(os.environ, PYTHONPATH=SHIM_DIR, **env_spec)
        r = subprocess.run(
            [sys.executable, "-P", REFERENCE, "--epochs", "1"],
            capture_output=True, text=True, timeout=120, env=env)
        assert r.returncode == 0, r.stderr[-2000:]
        # no process group: 4 iterations of batch 8, counts over 8
        assert r.stdout.count("Finish iteration") == 4
        assert "(7/16)" not in r.stdout

    @pytest.mark.slow
    def test_world2_loss_is_sum_over_ranks(self):
        """The reference prints reduce(loss) with op=SUM (the documented
        'average loss' comment is wrong — min_DDP.py:122); the primary's
        aggregated loss must equal the sum of the two per-rank losses.

        data-size 16 at batch 8 and world 2 = exactly one iteration per
        rank, so the association is unambiguous even though the two
        ranks' stdout interleaves."""
        import re

        r = _run_reference(2, "--epochs", "1", "--data-size", "16")
        assert r.returncode == 0, r.stderr[-2000:]
        per_rank = [float(v) for v in
                    re.findall(r"Loss:\s+([0-9]+\.[0-9]+)", r.stdout)]
        agg = [float(v) for v in
               re.findall(r"Finish iteration 0.*loss: ([0-9]+\.[0-9]+)",
                          r.stdout)]
        assert len(agg) == 1 and len(per_rank) == 2, r.stdout[-2000:]
        assert abs(agg[0] - sum(per_rank)) < 2e-3


def test_all_reduce_invalid_op_message_matches_reference():
    """The shim's invalid-op ValueError text is deliberately identical to
    reference distributed.py:131 (error-message parity — callers matching
    on the message see the same behavior). This test pins that rationale:
    if the string drifts from the reference's, one of the two must change
    knowingly."""
    sys.path.insert(0, SHIM_DIR)
    try:
        import distributed as shim
    finally:
        sys.path.pop(0)
    ref_line = '"{op}" is an invalid reduce operation!'
    with open("/root/reference/distributed.py") as f:
        assert ref_line in f.read()
    orig = shim.get_world_size
    shim.get_world_size = lambda: 2  # skip the world==1 short-circuit
    try:
        with pytest.raises(ValueError,
                           match='"prod" is an invalid reduce operation!'):
            shim.all_reduce(torch.zeros(3), op="prod")
    finally:
        shim.get_world_size = orig


class TestShardedSampler:
    def test_padding_when_world_exceeds_dataset(self):
        """total > 2*len(dataset): every rank still gets num_samples
        indices (repeat-wrap padding, the torch DistributedSampler
        contract) so no rank deadlocks with an empty shard."""
        sys.path.insert(0, SHIM_DIR)
        try:
            import distributed as shim
        finally:
            sys.path.pop(0)
        s = shim._ShardedSampler(list(range(2)), shuffle=False)
        s.world, s.rank = 5, 4
        s.num_samples = 1  # ceil(2/5)
        shards = []
        for rank in range(5):
            s.rank = rank
            shards.append(list(iter(s)))
        assert all(len(sh) == 1 for sh in shards)
        assert all(0 <= i < 2 for sh in shards for i in sh)


# ---------------------------------------------------------------------------
# Cross-implementation loss parity (same weights, same batches)
# ---------------------------------------------------------------------------

def _seeded_model(hidden=32, n_classes=4):
    torch.manual_seed(0)
    m = nn.Sequential()
    m.add_module("lin1", nn.Linear(1, hidden))
    m.add_module("lin2", nn.Linear(hidden, n_classes))
    return m


def _shard_batches(world, batch=4, steps=4, data_size=32, n_classes=4):
    """DummyDataset batches, rank-strided like DistributedSampler."""
    gen = torch.Generator().manual_seed(0)
    data = torch.arange(0, data_size, dtype=torch.float32).unsqueeze(-1)
    labels = torch.randint(0, n_classes, (data_size,), generator=gen)
    shards = []
    for rank in range(world):
        idx = list(range(rank, data_size, world))
        xs = [data[idx[i * batch:(i + 1) * batch]] for i in range(steps)]
        ys = [labels[idx[i * batch:(i + 1) * batch]] for i in range(steps)]
        shards.append((xs, ys))
    return shards


def _train_worker_shim(rank, world, out_path):
    """Runs in a spawned child: shim DDP over the native host group."""
    import distributed as dist  # the shim, via PYTHONPATH

    dist.init_process_group(rank, world)
    model = _seeded_model()
    model = dist.prepare_ddp_model(model, device_ids=[rank])
    opt = torch.optim.AdamW(model.parameters(), 1e-2)
    crit = nn.CrossEntropyLoss()
    xs, ys = _shard_batches(world)[rank]
    losses = []
    for x, y in zip(xs, ys):
        opt.zero_grad()
        loss = crit(model(x), y)
        loss.backward()
        opt.step()
        losses.append(float(loss))
    if rank == 0:
        with open(out_path, "w") as f:
            json.dump(losses, f)
    dist.cleanup()


def _train_worker_gloo(rank, world, port, out_path):
    """Runs in a spawned child: torch's own gloo DDP — the reference's
    actual CPU backend (reference distributed.py:64)."""
    import torch.distributed as tdist
    from torch.nn.parallel import DistributedDataParallel as TorchDDP

    os.environ["MASTER_ADDR"] = "localhost"
    os.environ["MASTER_PORT"] = str(port)
    tdist.init_process_group("gloo", rank=rank, world_size=world)
    model = TorchDDP(_seeded_model())
    opt = torch.optim.AdamW(model.parameters(), 1e-2)
    crit = nn.CrossEntropyLoss()
    xs, ys = _shard_batches(world)[rank]
    losses = []
    for x, y in zip(xs, ys):
        opt.zero_grad()
        loss = crit(model(x), y)
        loss.backward()
        opt.step()
        losses.append(float(loss))
    if rank == 0:
        with open(out_path, "w") as f:
            json.dump(losses, f)
    tdist.destroy_process_group()


def _spawn(target, world, args):
    import multiprocessing as mp

    ctx = mp.get_context("spawn")
    procs = [ctx.Process(target=target, args=(r, world) + args)
             for r in range(world)]
    try:
        for p in procs:
            p.start()
        for p in procs:
            p.join(timeout=180)
        assert all(p.exitcode == 0 for p in procs), \
            [p.exitcode for p in procs]
    finally:  # never leak hung children into the rest of the session
        for p in procs:
            if p.is_alive():
                p.terminate()
                p.join(timeout=5)
                if p.is_alive():
                    p.kill()


class TestCrossImplementationParity:
    @pytest.mark.slow
    def test_shim_ddp_matches_torch_gloo_ddp(self, tmp_path, monkeypatch):
        """world=2: the shim's grad-hook DDP over the native C++ group
        produces the same rank-0 loss trajectory as torch's own gloo
        DDP (the reference's CPU path) to float tolerance."""
        shim_out = str(tmp_path / "shim.json")
        gloo_out = str(tmp_path / "gloo.json")

        # monkeypatch restores sys.path/env after the test; spawn children
        # inherit the parent's sys.path via multiprocessing prep data, so
        # the shim dir must be ON sys.path while spawning
        monkeypatch.syspath_prepend(SHIM_DIR)
        import distributed as shim_dist
        monkeypatch.setenv("MASTER_ADDR", "localhost")
        monkeypatch.setenv("MASTER_PORT", str(shim_dist.find_free_port()))
        _spawn(_train_worker_shim, 2, (shim_out,))
        gloo_port = shim_dist.find_free_port()
        _spawn(_train_worker_gloo, 2, (gloo_port, gloo_out))

        shim_losses = json.load(open(shim_out))
        gloo_losses = json.load(open(gloo_out))
        np.testing.assert_allclose(shim_losses, gloo_losses,
                                   rtol=1e-5, atol=1e-6)

    def test_torch_weights_reproduce_in_jax_model(self):
        """VERDICT 'missing' #3: export torch-initialized DummyModel
        weights into the JAX model, feed identical batches, and the
        per-step losses match to float32 tolerance."""
        import jax
        import jax.numpy as jnp

        from distributed_pytorch_tpu import models, optim
        from distributed_pytorch_tpu.ops.losses import cross_entropy
        from distributed_pytorch_tpu.parallel import make_train_step

        tmodel = _seeded_model()
        crit = nn.CrossEntropyLoss()
        topt = torch.optim.AdamW(tmodel.parameters(), 1e-3)

        jmodel = models.DummyModel(in_dim=1, hidden_dim=32, n_classes=4)
        # export: torch Linear stores weight as (out, in); ours as (in, out).
        # jnp.array (not asarray): jax zero-copies numpy on CPU, and
        # tensor.numpy() shares the torch storage — without the copy,
        # topt.step() below would silently mutate the jax params too.
        def exp(t, transpose=False):
            a = t.detach().numpy()
            return jnp.array(a.T if transpose else a)

        params = {
            "lin1": {"w": exp(tmodel.lin1.weight, True),
                     "b": exp(tmodel.lin1.bias)},
            "lin2": {"w": exp(tmodel.lin2.weight, True),
                     "b": exp(tmodel.lin2.bias)},
        }

        def loss_fn(p, batch):
            x, y = batch
            return cross_entropy(jmodel.apply(p, x), y), {}

        opt = optim.adamw(1e-3)
        step = make_train_step(loss_fn, opt, donate=False)
        opt_state = opt.init(params)

        (xs, ys), = _shard_batches(world=1)
        t_losses, j_losses = [], []
        out_params, out_opt = params, opt_state
        for x, y in zip(xs, ys):
            topt.zero_grad()
            tl = crit(tmodel(x), y)
            tl.backward()
            topt.step()
            t_losses.append(float(tl.detach()))

            batch = (jnp.asarray(x.numpy()), jnp.asarray(y.numpy()))
            out = step(out_params, out_opt, batch)
            out_params, out_opt = out.params, out.opt_state
            j_losses.append(float(out.loss.mean()))

        np.testing.assert_allclose(t_losses, j_losses, rtol=2e-4, atol=1e-5)


class TestBucketedDDP:
    """Bucketed, overlapped gradient sync in the shim DDP (the torch
    reducer's design, SURVEY.md §2.3 row 4) — structure-level tests with a
    fake transport; the real-transport parity is covered by
    TestCrossImplementationParity."""

    class _FakeComm:
        world = 2
        rank = 0

        def __init__(self):
            self.allreduce_calls = 0
            self.allreduce_threads = set()

        def allreduce(self, arr):
            import threading as _t
            self.allreduce_calls += 1
            self.allreduce_threads.add(_t.current_thread().name)
            return arr * 2  # pretend the peer contributed identical grads

        def broadcast(self, arr, src=0):
            return arr

    def _shim(self):
        sys.path.insert(0, SHIM_DIR)
        try:
            import distributed as shim
        finally:
            sys.path.pop(0)
        return shim

    def _run_backward(self, shim, bucket_cap_mb):
        fake = self._FakeComm()
        old = shim._COMM
        shim._COMM = fake
        try:
            torch.manual_seed(0)
            model = nn.Sequential(*[nn.Linear(64, 64) for _ in range(6)])
            ddp = shim.DistributedDataParallel(model,
                                               bucket_cap_mb=bucket_cap_mb)
            x = torch.randn(4, 64)
            ddp(x).pow(2).mean().backward()
            grads = [p.grad.clone() for p in model.parameters()]
            return fake, model, grads
        finally:
            shim._COMM = old

    def test_buckets_coalesce_allreduces(self):
        shim = self._shim()
        # per-parameter mode: one ring op per parameter (12 of them)
        fake0, _, g0 = self._run_backward(shim, bucket_cap_mb=0)
        assert fake0.allreduce_calls == 12
        # default bucketing: the whole 100KB model fits one 25MB bucket
        fake1, _, g1 = self._run_backward(shim, bucket_cap_mb=25)
        assert fake1.allreduce_calls == 1
        # identical synchronized gradients either way (sum/world applied
        # on the flat bucket): fake doubles, world=2 -> grads unchanged
        for a, b in zip(g0, g1):
            np.testing.assert_allclose(a.numpy(), b.numpy(),
                                       rtol=1e-6, atol=1e-7)

    def test_bucket_partition_caps_and_order(self):
        shim = self._shim()
        fake, model, _ = self._run_backward(shim, bucket_cap_mb=0.02)
        # 0.02MB cap ~ 20KB; each 64x64 weight is 16KB -> weight+bias pairs
        # split across buckets, several ring ops but fewer than params
        assert 1 < fake.allreduce_calls < 12

    def test_reduction_runs_off_the_autograd_thread(self):
        """Overlap mechanism: bucket reduction happens on the comm worker
        thread, not inside the autograd hooks' thread."""
        shim = self._shim()
        fake, _, _ = self._run_backward(shim, bucket_cap_mb=25)
        import threading as _t
        assert fake.allreduce_threads, "no reductions recorded"
        assert _t.main_thread().name not in fake.allreduce_threads

    def test_unused_parameter_raises_instead_of_wedging(self):
        """A requires_grad parameter that produces no gradient must raise
        at the end of backward (torch DDP's contract without
        find_unused_parameters) — and must NOT poison the next backward
        (regression: the reducer used to wedge its comm thread forever
        and silently skip all future syncs)."""
        shim = self._shim()
        fake = self._FakeComm()
        old = shim._COMM
        shim._COMM = fake
        try:
            torch.manual_seed(0)

            class TwoHeads(nn.Module):
                def __init__(self):
                    super().__init__()
                    self.trunk = nn.Linear(8, 8)
                    self.used = nn.Linear(8, 4)
                    self.unused = nn.Linear(8, 4)

                def forward(self, x):
                    return self.used(self.trunk(x))

            ddp = shim.DistributedDataParallel(TwoHeads(), bucket_cap_mb=25)
            x = torch.randn(2, 8)
            with pytest.raises(RuntimeError, match="no gradient"):
                ddp(x).pow(2).mean().backward()
            # a subsequent complete backward on a fresh wrapper must work
            # normally (one DDP wrap per module, as with torch DDP)
            m2 = TwoHeads()
            for p in m2.unused.parameters():
                p.requires_grad_(False)
            ddp2 = shim.DistributedDataParallel(m2, bucket_cap_mb=25)
            ddp2(x).pow(2).mean().backward()
            assert all(p.grad is not None
                       for p in m2.parameters() if p.requires_grad)
        finally:
            shim._COMM = old
