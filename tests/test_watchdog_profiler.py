"""Failure detection (supervisor, heartbeats, orphan cleanup) and the
profiling subsystem. The reference has neither (SURVEY.md §5): its failure
handling is a manual kill command in the README and its profiling is
print statements — these tests pin down the automated replacements."""

import multiprocessing as mp
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_pytorch_tpu.runtime import (launch_multiprocess, watchdog)
from distributed_pytorch_tpu.runtime.watchdog import (
    WORKER_TAG_ENV, Heartbeat, HeartbeatMonitor, ProcessSupervisor,
    StalledWorker, WorkerFailure, find_tagged_workers, kill_orphan_workers)
from distributed_pytorch_tpu.utils import profiler


# module-level so they pickle under the spawn context
def _crasher(rank, world):
    if rank == 1:
        raise ValueError("rank 1 goes down")
    time.sleep(30)  # peers hang "in a collective"


def _sleeper_tagged(seconds):
    time.sleep(seconds)


def _ok_worker(rank, world):
    pass


class TestSupervisor:
    def test_fail_fast_terminates_hung_peers(self):
        """A crashed rank must bring the run down in seconds, not after the
        30s sleep of its peers (the reference would hang there)."""
        t0 = time.monotonic()
        with pytest.raises(WorkerFailure, match="rank 1 goes down"):
            launch_multiprocess(_crasher, 2)
        assert time.monotonic() - t0 < 20

    def test_clean_exit_no_error(self):
        launch_multiprocess(_ok_worker, 2)

    def test_supervisor_reports_exit_code(self):
        ctx = mp.get_context("spawn")
        p = ctx.Process(target=os._exit, args=(3,))
        p.start()
        with pytest.raises(WorkerFailure, match="exit code 3"):
            ProcessSupervisor([p]).join()


class TestHeartbeat:
    def test_beat_and_monitor(self, tmp_path):
        d = str(tmp_path)
        mon = HeartbeatMonitor(d, world_size=2)
        hb0 = Heartbeat(d, rank=0)
        hb1 = Heartbeat(d, rank=1)
        hb0.beat(step=5)
        hb1.beat(step=5)
        assert mon.stalled(timeout_s=10.0) == []
        mon.assert_alive(10.0)

    def test_stale_rank_detected(self, tmp_path):
        d = str(tmp_path)
        mon = HeartbeatMonitor(d, world_size=2)
        Heartbeat(d, rank=0).beat()
        time.sleep(0.3)
        # rank 1 never beat; rank 0's beacon is now older than the window
        assert mon.stalled(timeout_s=0.2) == [0, 1]
        with pytest.raises(StalledWorker):
            mon.assert_alive(0.2)

    def test_slow_starter_not_flagged_early(self, tmp_path):
        mon = HeartbeatMonitor(str(tmp_path), world_size=1)
        # no beacon yet, but the timeout window hasn't elapsed since start
        assert mon.stalled(timeout_s=60.0) == []


class TestOrphanCleanup:
    def test_find_and_kill_tagged(self):
        tag = f"test-orphan-{os.getpid()}"
        ctx = mp.get_context("spawn")
        old = os.environ.get(WORKER_TAG_ENV)
        os.environ[WORKER_TAG_ENV] = tag
        try:
            p = ctx.Process(target=_sleeper_tagged, args=(60,))
            p.start()
        finally:
            if old is None:
                os.environ.pop(WORKER_TAG_ENV, None)
            else:
                os.environ[WORKER_TAG_ENV] = old
        try:
            deadline = time.monotonic() + 5
            while time.monotonic() < deadline:
                if p.pid in find_tagged_workers(tag=tag):
                    break
                time.sleep(0.05)
            assert p.pid in find_tagged_workers(tag=tag)
            killed = kill_orphan_workers(tag=tag)
            assert p.pid in killed
            p.join(10)
            assert p.exitcode is not None and p.exitcode != 0
        finally:
            if p.is_alive():
                p.kill()
                p.join()

    def test_nonexistent_tag_matches_nothing(self):
        assert find_tagged_workers(tag="no-such-tag-ever") == []

    @staticmethod
    def _spawn_tagged(tag, seconds=60):
        ctx = mp.get_context("spawn")
        old = os.environ.get(WORKER_TAG_ENV)
        os.environ[WORKER_TAG_ENV] = tag
        try:
            p = ctx.Process(target=_sleeper_tagged, args=(seconds,))
            p.start()
        finally:
            if old is None:
                os.environ.pop(WORKER_TAG_ENV, None)
            else:
                os.environ[WORKER_TAG_ENV] = old
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            if p.pid in find_tagged_workers(tag=tag):
                return p
            time.sleep(0.05)
        return p

    def test_exclude_tag_spares_that_launch(self):
        tag = f"test-excl-{os.getpid()}"
        p = self._spawn_tagged(tag)
        try:
            # the excluded tag must survive a blanket kill...
            killed = kill_orphan_workers(exclude_tag=tag)
            assert p.pid not in killed and p.is_alive()
            # ...and a targeted kill takes it down
            assert p.pid in kill_orphan_workers(tag=tag)
        finally:
            if p.is_alive():
                p.kill()
            p.join()

    def test_concurrent_jobs_live_workers_spared(self):
        """A blanket cleanup from one process must not kill another job's
        live workers: _ACTIVE_TAGS is per-process and cannot see them, so
        orphan-ness is decided by the liveness of the launcher pid encoded
        in the tag (regression: this used to kill any tagged process)."""
        ctx = mp.get_context("spawn")
        launcher = ctx.Process(target=_sleeper_tagged, args=(60,))
        launcher.start()  # stands in for a concurrent job's live launcher
        tag = f"{launcher.pid}-123456"  # the launch-tag format
        worker = self._spawn_tagged(tag)
        try:
            # blanket cleanup: the worker's launcher is alive -> spared
            killed = kill_orphan_workers()
            assert worker.pid not in killed and worker.is_alive()
            # even an explicit-tag kill respects liveness by default...
            assert worker.pid not in kill_orphan_workers(tag=tag)
            # ...unless forced
            launcher.kill()
            launcher.join()
            # launcher dead -> now a genuine orphan, collected
            deadline = time.monotonic() + 5
            while time.monotonic() < deadline:
                if worker.pid in find_tagged_workers(tag=tag):
                    break
                time.sleep(0.05)
            assert worker.pid in kill_orphan_workers(tag=tag)
        finally:
            for p in (launcher, worker):
                if p.is_alive():
                    p.kill()
                p.join()

    def test_active_launch_spared_by_default(self):
        tag = f"test-active-{os.getpid()}"
        p = self._spawn_tagged(tag)
        watchdog.register_active_tag(tag)
        try:
            assert p.pid not in kill_orphan_workers()
            assert p.is_alive()
            watchdog.unregister_active_tag(tag)
            assert p.pid in kill_orphan_workers(tag=tag)
        finally:
            watchdog.unregister_active_tag(tag)
            if p.is_alive():
                p.kill()
            p.join()


class TestProfiler:
    def test_step_timer_summary(self):
        timer = profiler.StepTimer(warmup=1)
        x = jnp.ones((64, 64))
        f = jax.jit(lambda x: x @ x)
        timer.measure(f, x, n=5)
        s = timer.summary()
        assert s["steps"] == 5
        assert s["mean_s"] > 0 and s["steps_per_sec"] > 0
        assert timer.warmup_times and len(timer.times) == 5
        assert timer.throughput(items_per_step=64) == \
            pytest.approx(64 * s["steps_per_sec"])

    def test_measure_reuse_separates_warmup(self):
        """A reused timer must not count the second call's warmup
        (compile) iterations as timed samples."""
        timer = profiler.StepTimer(warmup=1)
        x = jnp.ones((16, 16))
        timer.measure(jax.jit(lambda x: x + 1), x, n=3)
        timer.measure(jax.jit(lambda x: x * 3), x, n=3)  # fresh compile
        assert len(timer.times) == 6
        assert len(timer.warmup_times) == 2

    def test_step_context_manager_fences(self):
        timer = profiler.StepTimer(warmup=0)
        f = jax.jit(lambda x: x * 2)
        with timer.step() as h:
            h["fence"] = f(jnp.ones((8, 8)))
        assert timer.count == 1

    def test_compiled_stats_flops(self):
        n = 128
        stats = profiler.compiled_stats(
            lambda a, b: a @ b, jnp.ones((n, n)), jnp.ones((n, n)))
        # XLA's cost model: 2*n^3 flops for a dense matmul
        assert stats.get("flops", 0) == pytest.approx(2 * n ** 3, rel=0.1)

    def test_trace_writes_profile(self, tmp_path):
        d = str(tmp_path / "prof")
        with profiler.trace(d):
            jax.block_until_ready(jax.jit(lambda x: x + 1)(jnp.ones(8)))
        found = [f for _, _, fs in os.walk(d) for f in fs]
        assert any(f.endswith(".xplane.pb") for f in found)

    def test_annotate_runs(self):
        with profiler.annotate("region"):
            pass

    def test_fetch_fence_pytree(self):
        out = jax.jit(lambda x: {"a": x + 1, "b": (x * 2,)})(jnp.ones(4))
        profiler.fetch_fence(out)  # must not raise, must materialize

    def test_step_timer_fetch_mode(self):
        timer = profiler.StepTimer(warmup=1, fetch=True)
        f = jax.jit(lambda x: x @ x)
        x = jnp.ones((64, 64))
        timer.measure(f, x, n=3)
        s = timer.summary()
        assert s["steps"] == 3 and s["median_s"] > 0

    def test_time_steps_amortized_chains_state(self):
        f = jax.jit(lambda x: x + 1.0)
        x0 = jnp.zeros(())
        per_step, xn = profiler.time_steps_amortized(
            f, x0, 10, lambda x: x)
        assert per_step > 0
        assert float(xn) == 10.0
