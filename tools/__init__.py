"""Developer CLIs: ``python -m tools.dpxlint`` (invariant lint, PR 5)
and ``python -m tools.gen_env_docs`` (regenerate docs/env_vars.md from
the typed registry)."""
