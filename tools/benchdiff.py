"""benchdiff CLI — diff a benchmark record against the BENCH trajectory.

Usage::

    python -m tools.benchdiff                     # newest stored record
                                                  # vs the rows before it
    python -m tools.benchdiff --record rec.json   # explicit new record
    python -m tools.benchdiff --record -          # record on stdin
    python -m tools.benchdiff --log FILE          # non-default store
    python -m tools.benchdiff --min-drop 0.05     # sensitivity floor
    python -m tools.benchdiff --strict            # malformed store lines
                                                  # are fatal

Compares every trusted *measured* metric of the new record against the
newest trusted measured baseline for the same metric in the trajectory
store (``benchmarks/tpu_results.jsonl``) and prints an attributed
report.  A change counts as a regression only when it exceeds
``max(min_drop, baseline spread, new spread)`` in the metric's worse
direction — the same spread gate that governs ``vs_baseline``
(docs/benchmarking.md).

Exit codes: 0 = no regression, 1 = regression (CI fails the bench-smoke
job on this), 2 = usage / invalid record / corrupt store in --strict.

Like ``tools/dpxlint.py``, this deliberately avoids the heavy package
``__init__`` (which pulls jax): the perfbench record/trajectory modules
are stdlib-only and load against fabricated lightweight parent packages,
so the diff runs in a bare CI job in milliseconds.
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def _load_perfbench():
    """Import the perfbench modules.  The REAL package is tried first —
    a fabricated skeleton left in sys.modules would permanently shadow
    the genuine package __init__ for the rest of the process.  Only
    when the real import chain fails (a bare venv where the package
    __init__ pulls jax) are lightweight parent packages fabricated so
    the stdlib-only perfbench modules resolve against the source tree.

    NOT shared with benchmarks/report.py's private-name loader on
    purpose: trajectory.diff's default min_drop resolves through
    ``..runtime.env``, which only works under the real package name —
    fine for this CLI-owned process, unacceptable for report.py, which
    must never import the real package (jax-free watcher contract) and
    therefore loads record-only under a private name."""
    import importlib
    import types

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sys.path.insert(0, root)
    try:
        return importlib.import_module("distributed_pytorch_tpu.perfbench")
    except Exception:  # noqa: BLE001 — bare venv: the __init__ chain needs jax
        pass
    pkg_dir = os.path.join(root, "distributed_pytorch_tpu")
    for name, sub in (("distributed_pytorch_tpu", ""),
                      ("distributed_pytorch_tpu.runtime", "runtime"),
                      ("distributed_pytorch_tpu.utils", "utils")):
        if name not in sys.modules:
            pkg = types.ModuleType(name)
            pkg.__path__ = [os.path.join(pkg_dir, sub) if sub
                            else pkg_dir]
            sys.modules[name] = pkg
    return importlib.import_module("distributed_pytorch_tpu.perfbench")


def main(argv=None) -> int:
    pb = _load_perfbench()
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    default_log = os.path.join(root, "benchmarks", "tpu_results.jsonl")

    ap = argparse.ArgumentParser(prog="benchdiff", description=__doc__)
    ap.add_argument("--log", default=default_log,
                    help="trajectory store (default: "
                         "benchmarks/tpu_results.jsonl)")
    ap.add_argument("--record", default=None, metavar="FILE|-",
                    help="new record to diff (JSON file, or - for "
                         "stdin); default: the newest schema record in "
                         "the store, diffed against the rows before it")
    ap.add_argument("--min-drop", type=float, default=None,
                    help="sensitivity floor (default: "
                         "DPX_BENCH_MIN_DROP)")
    ap.add_argument("--strict", action="store_true",
                    help="malformed trajectory lines / invalid records "
                         "are fatal (exit 2)")
    args = ap.parse_args(argv)

    try:
        rows, malformed = pb.record.iter_rows(args.log,
                                              strict=args.strict)
    except pb.RecordInvalid as e:
        print(f"benchdiff: {e}", file=sys.stderr)
        return 2
    for line_no, reason in malformed:
        print(f"# benchdiff: skipping malformed store line {line_no}: "
              f"{reason}", file=sys.stderr)

    if args.record is not None:
        try:
            text = (sys.stdin.read() if args.record == "-"
                    else open(args.record, encoding="utf-8").read())
            new_rec = json.loads(text)
        except (OSError, json.JSONDecodeError) as e:
            print(f"benchdiff: cannot read record: {e}", file=sys.stderr)
            return 2
        # bench.py self-logs its record to the store by default — if the
        # record under test already landed there, diffing it against its
        # own row would mask every regression as "unchanged 0%"
        base_rows = [r for r in rows if r.get("result") != new_rec]
    else:
        # newest schema record in the store is "new"; everything before
        # its row is the baseline trajectory.  Row-level ok is not
        # required: an unmeasured-flagship record logs ok=false, but its
        # trusted measured metrics (the per-blob gate decides) must
        # still be regression-checked — on a TPU-less container these
        # are the only fresh numbers there are.
        idx = None
        for i, row in enumerate(rows):
            res = row.get("result", {})
            if (not row.get("retracted") and isinstance(res, dict)
                    and res.get("schema") == pb.record.SCHEMA):
                idx = i
        if idx is None:
            print("benchdiff: no schema records in the trajectory yet — "
                  "nothing to compare")
            return 0
        new_rec = rows[idx]["result"]
        base_rows = rows[:idx]

    issues = pb.record.validate_record(new_rec, strict=False)
    if issues:
        msg = (f"benchdiff: new record fails schema validation: "
               + "; ".join(issues[:5]))
        print(msg, file=sys.stderr)
        if args.strict:
            return 2
        print("# benchdiff: diffing what can be diffed anyway "
              "(non-strict)", file=sys.stderr)

    report = pb.trajectory.diff(new_rec, base_rows,
                                min_drop=args.min_drop)
    print(report.format())
    print(json.dumps({
        "regressions": len(report.regressions),
        "improvements": len(report.improvements),
        "unchanged": len(report.unchanged),
        "skipped": len(report.skipped),
        "ok": report.ok,
    }))
    return 0 if report.ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
