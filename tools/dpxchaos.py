"""dpxchaos CLI — validate chaos-campaign declarations and roll up
campaign reports (runtime/chaos.py + benchmarks/chaos_campaign.py —
docs/failures.md "Chaos campaigns").

Usage::

    python -m tools.dpxchaos validate SPEC
                            # SPEC = a DPX_CHAOS value: inline JSON, a
                            # .json path, or the compact clause grammar.
                            # Prints the expanded clause table (grid
                            # clauses multiplied out, every fault spec
                            # parsed against the registered op
                            # vocabulary); exit 1 with the typed parse
                            # error on any bad clause
    python -m tools.dpxchaos report REPORT.json
                            # REPORT.json = a chaos_campaign.py
                            # campaign_report: per-clause verdict table
                            # (fired / typed error / attributed /
                            # recovered / green) + the rollup line;
                            # exit 0 only when EVERY clause is green

Exit codes: 0 = valid / all green, 1 = parse error or non-green
clause(s), 2 = usage / unreadable input.

Like ``tools/dpxmon.py`` and ``tools/benchdiff.py``, this avoids the
heavy package ``__init__`` (which pulls jax): ``runtime/chaos.py`` and
its imports (``runtime/env.py``, ``runtime/faults.py``) are
stdlib-only and load against fabricated lightweight parents, so the
CLI runs in a bare venv in milliseconds.
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def _load_chaos():
    """Import ``distributed_pytorch_tpu.runtime.chaos``: the REAL
    package first (in-process test use), else fabricated lightweight
    parents so the stdlib-only runtime modules resolve against the
    source tree (the benchdiff/dpxmon loader contract)."""
    import importlib
    import types

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sys.path.insert(0, root)
    try:
        return importlib.import_module(
            "distributed_pytorch_tpu.runtime.chaos")
    except Exception:  # noqa: BLE001 — bare venv: the __init__ chain needs jax
        pass
    pkg_dir = os.path.join(root, "distributed_pytorch_tpu")
    for name, sub in (("distributed_pytorch_tpu", ""),
                      ("distributed_pytorch_tpu.runtime", "runtime")):
        if name not in sys.modules:
            pkg = types.ModuleType(name)
            pkg.__path__ = [os.path.join(pkg_dir, sub) if sub
                            else pkg_dir]
            sys.modules[name] = pkg
    return importlib.import_module(
        "distributed_pytorch_tpu.runtime.chaos")


def _fmt_table(rows, cols):
    if not rows:
        return ""
    widths = [max(len(str(c)), *(len(str(r.get(c, ""))) for r in rows))
              for c in cols]
    out = ["  ".join(str(c).ljust(w) for c, w in zip(cols, widths))]
    out.append("  ".join("-" * w for w in widths))
    for r in rows:
        out.append("  ".join(str(r.get(c, "")).ljust(w)
                             for c, w in zip(cols, widths)))
    return "\n".join(out)


def cmd_validate(chaos, args) -> int:
    try:
        campaign = chaos.parse_campaign(args.spec)
    except (ValueError, OSError) as e:
        print(f"dpxchaos: invalid campaign: {e}", file=sys.stderr)
        return 1
    rows = [{"id": c.id, "leg": c.leg, "expect": c.expect,
             "fault": c.fault,
             "env": " ".join(f"{k}={v}" for k, v in c.env.items())}
            for c in campaign.clauses]
    print(f"campaign {campaign.name!r}: {len(rows)} clause(s)")
    print(_fmt_table(rows, ("id", "leg", "expect", "fault", "env")))
    return 0


def cmd_report(chaos, args) -> int:
    try:
        with open(args.report, "r", encoding="utf-8") as f:
            report = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        print(f"dpxchaos: cannot read report {args.report}: {e}",
              file=sys.stderr)
        return 2
    rows = report.get("clauses")
    if not isinstance(rows, list) or not rows:
        print("dpxchaos: report carries no 'clauses' list",
              file=sys.stderr)
        return 2
    shown = []
    for r in rows:
        shown.append({
            "id": r.get("id", "?"), "leg": r.get("leg", "?"),
            "expect": r.get("expect", "?"),
            "fault": r.get("fault", "?"),
            "fired": r.get("fired", False),
            "typed_error": r.get("typed_error", "") or "-",
            "attributed": r.get("attributed", False),
            "recovered": r.get("recovered", False),
            "retries": r.get("retries", 0),
            "green": chaos.clause_green(r),
        })
    print(_fmt_table(shown, ("id", "leg", "expect", "fault", "fired",
                             "typed_error", "attributed", "recovered",
                             "retries", "green")))
    verdict = chaos.campaign_verdict(rows)
    name = report.get("name", "campaign")
    print(f"{name}: {verdict['green']}/{verdict['clauses']} clause(s) "
          f"green" + ("" if verdict["ok"]
                      else f" — NOT GREEN: {verdict['failing']}"))
    return 0 if verdict["ok"] else 1


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="dpxchaos",
        description="validate chaos campaigns / roll up their reports")
    sub = parser.add_subparsers(dest="cmd", required=True)
    p_val = sub.add_parser(
        "validate", help="parse+expand a DPX_CHAOS campaign spec")
    p_val.add_argument("spec", help="inline JSON, a .json path, or the "
                                    "compact clause grammar")
    p_rep = sub.add_parser(
        "report", help="per-clause verdict table from a campaign "
                       "report JSON")
    p_rep.add_argument("report", help="campaign_report.json path")
    args = parser.parse_args(argv)
    chaos = _load_chaos()
    try:
        if args.cmd == "validate":
            return cmd_validate(chaos, args)
        return cmd_report(chaos, args)
    except BrokenPipeError:
        # piped into head: exit quietly, not with a traceback
        os.close(sys.stderr.fileno())
        return 0


if __name__ == "__main__":
    raise SystemExit(main())
