"""dpxlint CLI — run the repo invariant lint (analysis/lint.py).

Usage::

    python -m tools.dpxlint                  # lint repo, baseline applied
    python -m tools.dpxlint --no-baseline    # every finding, raw
    python -m tools.dpxlint --write-baseline # accept current findings
    python -m tools.dpxlint path/ other.py   # restrict to paths

Exit code 0 = clean (no findings outside the committed baseline),
1 = new findings, 2 = a linted file failed to parse. CI runs
``python -m tools.dpxlint --baseline`` as the fast lint job
(.github/workflows/tier1.yml); the rule catalog is docs/analysis.md.

This module deliberately avoids importing jax (or any package module
with heavy imports): the lint must run in a bare CI job in
milliseconds. ``analysis.lint`` imports only stdlib + the env registry.
"""

from __future__ import annotations

import argparse
import os
import sys


def _load_lint():
    """Import analysis.lint WITHOUT executing the package __init__ (which
    pulls jax): fabricate lightweight parent packages so the module's
    relative imports resolve against the source tree. setdefault keeps
    an already-imported real package (in-process test use) intact."""
    import importlib
    import types

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sys.path.insert(0, root)
    if "distributed_pytorch_tpu" not in sys.modules:
        pkg = types.ModuleType("distributed_pytorch_tpu")
        pkg.__path__ = [os.path.join(root, "distributed_pytorch_tpu")]
        sys.modules["distributed_pytorch_tpu"] = pkg
    return importlib.import_module(
        "distributed_pytorch_tpu.analysis.lint")


def main(argv=None) -> int:
    lint = _load_lint()

    ap = argparse.ArgumentParser(prog="dpxlint", description=__doc__)
    ap.add_argument("paths", nargs="*",
                    help="files/dirs to lint (default: repo root)")
    ap.add_argument("--baseline", nargs="?", const=lint.DEFAULT_BASELINE,
                    default=lint.DEFAULT_BASELINE, metavar="FILE",
                    help="baseline file (default: committed baseline)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report every finding, ignoring the baseline")
    ap.add_argument("--write-baseline", action="store_true",
                    help="write current findings as the new baseline")
    args = ap.parse_args(argv)

    root = lint.repo_root()
    findings = lint.lint_paths(args.paths or None, root=root)

    parse_failures = [f for f in findings if f.rule == "DPX000"]
    findings = [f for f in findings if f.rule != "DPX000"]

    baseline_path = (args.baseline if os.path.isabs(args.baseline)
                     else os.path.join(root, args.baseline))
    if args.write_baseline:
        lint.save_baseline(baseline_path, findings)
        print(f"dpxlint: wrote {len(findings)} finding(s) to "
              f"{os.path.relpath(baseline_path, root)}")
        return 0

    if not args.no_baseline and os.path.exists(baseline_path):
        findings = lint.apply_baseline(
            findings, lint.load_baseline(baseline_path))

    for f in parse_failures:
        print(str(f), file=sys.stderr)
    for f in findings:
        print(str(f))
    if parse_failures:
        return 2
    if findings:
        print(f"dpxlint: {len(findings)} new finding(s) — fix, add "
              "'# dpxlint: disable=DPXnnn <reason>', or re-baseline "
              "(docs/analysis.md)", file=sys.stderr)
        return 1
    print("dpxlint: clean")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
