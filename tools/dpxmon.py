"""dpxmon CLI — follow or replay the live metrics stream, render
per-rank tables and the streaming SLO health verdict (obs/metrics.py +
obs/health.py — docs/observability.md).

Usage::

    python -m tools.dpxmon replay LOG [LOG ...] [--rules SPEC]
                            # full pass: strict-validate every
                            # metrics_snapshot, re-derive the health
                            # trajectory, print transitions (rank+rule
                            # attributed) and per-rank tables;
                            # exit 1 on any CRITICAL verdict or
                            # validation issue
    python -m tools.dpxmon follow LOG [--interval S] [--max-seconds S]
                            # tail a LIVE log, re-render health state as
                            # snapshots arrive; exits 1 the moment the
                            # monitor goes critical
    python -m tools.dpxmon check LOG [LOG ...]
                            # strict snapshot validation only

``--rules`` takes the obs/health.py rule grammar
(``serve.ttft_ms.p99<=500;drift(train.steps_per_sec)``); the default is
``health.DEFAULT_RULES``. Exit codes: 0 = healthy/clean, 1 = critical
verdict or validation issues, 2 = usage / unreadable input.

Like ``tools/dpxtrace.py`` and ``tools/benchdiff.py``, this avoids the
heavy package ``__init__`` (which pulls jax): obs/ and perfbench/ are
stdlib-only and load against fabricated lightweight parents, so the CLI
runs in a bare venv in milliseconds.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time


def _load_obs():
    """Import ``distributed_pytorch_tpu.obs``: the REAL package first
    (in-process test use), else fabricated lightweight parents so the
    stdlib-only obs/perfbench modules resolve against the source tree
    (the benchdiff/dpxtrace loader contract)."""
    import importlib
    import types

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sys.path.insert(0, root)
    try:
        return importlib.import_module("distributed_pytorch_tpu.obs")
    except Exception:  # noqa: BLE001 — bare venv: the __init__ chain needs jax
        pass
    pkg_dir = os.path.join(root, "distributed_pytorch_tpu")
    for name, sub in (("distributed_pytorch_tpu", ""),
                      ("distributed_pytorch_tpu.runtime", "runtime"),
                      ("distributed_pytorch_tpu.utils", "utils")):
        if name not in sys.modules:
            pkg = types.ModuleType(name)
            pkg.__path__ = [os.path.join(pkg_dir, sub) if sub
                            else pkg_dir]
            sys.modules[name] = pkg
    return importlib.import_module("distributed_pytorch_tpu.obs")


def _read_all(obs, paths):
    records, malformed = [], []
    for path in paths:
        try:
            recs, bad = obs.export.read_log(path)
        except OSError as e:
            print(f"dpxmon: cannot read {path}: {e}", file=sys.stderr)
            raise SystemExit(2)
        for r in recs:
            r["_path"] = path
        records.extend(recs)
        malformed.extend((path, ln, why) for ln, why in bad)
    return records, malformed


def _fmt_table(rows, cols):
    if not rows:
        return "(none)"
    widths = {c: max(len(c), *(len(str(r.get(c, ""))) for r in rows))
              for c in cols}
    head = "  ".join(c.ljust(widths[c]) for c in cols)
    sep = "  ".join("-" * widths[c] for c in cols)
    body = "\n".join("  ".join(str(r.get(c, "")).ljust(widths[c])
                               for c in cols) for r in rows)
    return "\n".join([head, sep, body])


def _fmt_metric(v):
    if isinstance(v, dict):
        return f"p50={v.get('p50')} p99={v.get('p99')} n={v.get('count')}"
    if isinstance(v, float):
        return f"{v:.4g}"
    return v


def _rank_tables(snapshots):
    """Latest snapshot per (rank, source) -> printable rows."""
    latest = {}
    for rec in snapshots:
        latest[(rec.get("rank"), rec.get("source"))] = rec
    rows = []
    for (rank, source), rec in sorted(
            latest.items(),
            key=lambda kv: (kv[0][0] is None, kv[0][0], kv[0][1] or "")):
        for name in sorted(rec.get("metrics", {})):
            rows.append({"rank": rank, "source": source, "metric": name,
                         "value": _fmt_metric(rec["metrics"][name]),
                         "step": rec.get("step")})
    return rows


def _validate(obs, records, malformed):
    issues = [f"{path}:{ln}: malformed line: {why}"
              for path, ln, why in malformed]
    for rec in records:
        if rec.get("event") != "metrics_snapshot":
            continue
        for msg in obs.metrics.validate_snapshot(rec):
            issues.append(f"{rec.get('_path')}:{rec.get('_line')}: {msg}")
    return issues


def _monitor_for(obs, args):
    rules = obs.health.parse_rules(args.rules) if args.rules else None
    return obs.health.HealthMonitor(rules)


def _print_verdict(mon) -> None:
    v = mon.verdict()
    if v["transitions"]:
        print("health transitions:")
        print(_fmt_table(
            [{"from": t["from"], "to": t["to"], "rule": t["rule"],
              "metric": t["metric"], "rank": t["rank"],
              "value": t["value"]} for t in v["transitions"]],
            ("from", "to", "rule", "metric", "rank", "value")))
    else:
        print("health transitions: (none)")
    firing = v["firing"]
    if firing:
        print("firing rules:")
        print(_fmt_table(firing,
                         ("rule", "rank", "state", "breaches", "value")))
    print(f"health: {v['state'].upper()} "
          f"({v['snapshots']} snapshot(s) evaluated)")


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    ap = argparse.ArgumentParser(prog="dpxmon", description=__doc__)
    sub = ap.add_subparsers(dest="cmd", required=True)
    for name in ("replay", "check", "follow"):
        p = sub.add_parser(name)
        p.add_argument("logs", nargs="+",
                       help="line-JSON metrics log(s)")
        if name in ("replay", "follow"):
            p.add_argument("--rules", default=None,
                           help="SLO rule spec (obs/health.py grammar; "
                                "default: the built-in rule set)")
        if name == "follow":
            p.add_argument("--interval", type=float, default=2.0,
                           help="poll interval seconds (default 2)")
            p.add_argument("--max-seconds", type=float, default=None,
                           help="stop following after this long "
                                "(default: forever)")
    args = ap.parse_args(argv)
    obs = _load_obs()

    if args.cmd == "follow":
        if len(args.logs) != 1:
            print("dpxmon follow takes exactly one log", file=sys.stderr)
            return 2
        mon = _monitor_for(obs, args)
        follower = obs.health.LogFollower(args.logs[0], mon)
        t0 = time.monotonic()
        while True:
            for tr in follower.poll():
                print(f"# health {tr['from']} -> {tr['to']} "
                      f"(rule {tr['rule']}, metric {tr['metric']}, "
                      f"rank {tr['rank']}, value {tr['value']})",
                      flush=True)
            if mon.state == "critical":
                _print_verdict(mon)
                return 1
            if (args.max_seconds is not None
                    and time.monotonic() - t0 >= args.max_seconds):
                _print_verdict(mon)
                return 0
            time.sleep(args.interval)

    records, malformed = _read_all(obs, args.logs)
    issues = _validate(obs, records, malformed)

    if args.cmd == "check":
        for msg in issues:
            print(msg)
        n = sum(1 for r in records
                if r.get("event") == "metrics_snapshot")
        if issues:
            print(f"dpxmon check: {len(issues)} issue(s)",
                  file=sys.stderr)
            return 1
        print(f"dpxmon check: clean ({n} snapshot(s) across "
              f"{len(args.logs)} log(s))")
        return 0

    # replay: records in time order (the multi-writer stream is
    # monotone per process; cross-process skew is below the snapshot
    # cadence, so a global time sort is the honest replay order)
    records.sort(key=lambda r: (r.get("time") is None,
                                r.get("time", 0.0)))
    mon = _monitor_for(obs, args)
    ever_critical = False
    for rec in records:
        mon.feed(rec)
        ever_critical = ever_critical or mon.state == "critical"
    snapshots = [r for r in records
                 if r.get("event") == "metrics_snapshot"]
    print(_fmt_table(_rank_tables(snapshots),
                     ("rank", "source", "step", "metric", "value")))
    for msg in issues:
        print(f"# validation: {msg}")
    _print_verdict(mon)
    if issues:
        print(f"dpxmon replay: {len(issues)} validation issue(s)",
              file=sys.stderr)
        return 1
    if ever_critical:
        print("dpxmon replay: CRITICAL health verdict", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    try:
        raise SystemExit(main())
    except BrokenPipeError:
        # `dpxmon replay | head` is a legitimate spelling — exit
        # quietly on a closed pipe instead of tracebacking
        import os as _os
        _os.close(2)
        raise SystemExit(0)
