"""dpxtrace CLI — merge, export, summarize and police the cross-rank
span logs (obs/ — docs/observability.md).

Usage::

    python -m tools.dpxtrace export LOG [LOG ...] -o trace.json
                                        # merged Chrome trace-event JSON
                                        # (chrome://tracing / Perfetto)
    python -m tools.dpxtrace merge LOG [LOG ...] -o merged.jsonl
                                        # concatenate per-rank line-JSON
                                        # logs (validated, line-attributed)
    python -m tools.dpxtrace summarize LOG [LOG ...]
                                        # per-op per-rank duration table
    python -m tools.dpxtrace stragglers LOG [LOG ...] [--k 3.0]
                                        # ranks outside k*IQR per op
    python -m tools.dpxtrace check LOG  # strict metrics-log validator:
                                        # malformed lines (with line
                                        # numbers), unknown event names,
                                        # rank-unattributed failure
                                        # events; exit 1 on any issue

``--check LOG`` is accepted as an alias for the ``check`` subcommand.

Exit codes: 0 = ok, 1 = issues found (check) / stragglers flagged with
``--fail-on-straggler``, 2 = usage or unreadable input.

Like ``tools/dpxlint.py`` and ``tools/benchdiff.py``, this deliberately
avoids the heavy package ``__init__`` (which pulls jax): the obs and
perfbench modules are stdlib-only and load against fabricated
lightweight parent packages, so the CLI runs in a bare venv in
milliseconds.
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def _load_obs():
    """Import ``distributed_pytorch_tpu.obs``: the REAL package first
    (in-process test use), else fabricated lightweight parents so the
    stdlib-only obs/perfbench modules resolve against the source tree
    (the benchdiff loader contract)."""
    import importlib
    import types

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sys.path.insert(0, root)
    try:
        return importlib.import_module("distributed_pytorch_tpu.obs")
    except Exception:  # noqa: BLE001 — bare venv: the __init__ chain needs jax
        pass
    pkg_dir = os.path.join(root, "distributed_pytorch_tpu")
    for name, sub in (("distributed_pytorch_tpu", ""),
                      ("distributed_pytorch_tpu.runtime", "runtime"),
                      ("distributed_pytorch_tpu.utils", "utils")):
        if name not in sys.modules:
            pkg = types.ModuleType(name)
            pkg.__path__ = [os.path.join(pkg_dir, sub) if sub
                            else pkg_dir]
            sys.modules[name] = pkg
    return importlib.import_module("distributed_pytorch_tpu.obs")


def _read_all(obs, paths):
    """(records, malformed-with-path) across the given logs, in path
    order then line order — the merge."""
    records, malformed = [], []
    for path in paths:
        try:
            recs, bad = obs.export.read_log(path)
        except OSError as e:
            print(f"dpxtrace: cannot read {path}: {e}", file=sys.stderr)
            raise SystemExit(2)
        for r in recs:
            r["_path"] = path
        records.extend(recs)
        malformed.extend((path, ln, why) for ln, why in bad)
    return records, malformed


def _fmt_table(rows, cols):
    if not rows:
        return "(no spans)"
    widths = {c: max(len(c), *(len(str(r.get(c, ""))) for r in rows))
              for c in cols}
    head = "  ".join(c.ljust(widths[c]) for c in cols)
    sep = "  ".join("-" * widths[c] for c in cols)
    body = "\n".join("  ".join(str(r.get(c, "")).ljust(widths[c])
                               for c in cols) for r in rows)
    return "\n".join([head, sep, body])


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    # --check LOG alias (the ISSUE-facing spelling)
    if argv and argv[0] == "--check":
        argv = ["check"] + argv[1:]

    ap = argparse.ArgumentParser(prog="dpxtrace", description=__doc__)
    sub = ap.add_subparsers(dest="cmd", required=True)
    for name in ("export", "merge", "summarize", "stragglers", "check"):
        p = sub.add_parser(name)
        p.add_argument("logs", nargs="+",
                       help="line-JSON metrics/span log(s)")
        if name in ("export", "merge"):
            p.add_argument("-o", "--out", default="-",
                           help="output file (default: stdout)")
        if name == "export":
            p.add_argument("--no-align", action="store_true",
                           help="skip cross-rank clock alignment")
        if name == "stragglers":
            p.add_argument("--k", type=float, default=None,
                           help="IQR multiplier (default 3.0)")
            p.add_argument("--fail-on-straggler", action="store_true",
                           help="exit 1 when any rank is flagged")
    args = ap.parse_args(argv)

    obs = _load_obs()
    records, malformed = _read_all(obs, args.logs)

    if args.cmd == "check":
        issues = []
        for path, ln, why in malformed:
            issues.append(f"{path}:{ln}: malformed line: {why}")
        for rec in records:
            found = obs.export.check_log([rec], [])
            for ln, msg in found:
                issues.append(f"{rec.get('_path')}:{ln}: {msg}")
        for msg in issues:
            print(msg)
        if issues:
            print(f"dpxtrace check: {len(issues)} issue(s)",
                  file=sys.stderr)
            return 1
        print(f"dpxtrace check: clean ({len(records)} record(s) across "
              f"{len(args.logs)} log(s))")
        return 0

    for path, ln, why in malformed:
        print(f"# dpxtrace: skipping malformed line {path}:{ln}: {why}",
              file=sys.stderr)

    if args.cmd == "merge":
        out = (sys.stdout if args.out == "-"
               else open(args.out, "w", encoding="utf-8"))
        try:
            for rec in records:
                rec = {k: v for k, v in rec.items()
                       if k not in ("_line", "_path")}
                out.write(json.dumps(rec, default=str) + "\n")
        finally:
            if out is not sys.stdout:
                out.close()
        print(f"# dpxtrace: merged {len(records)} record(s)",
              file=sys.stderr)
        return 0

    if args.cmd == "export":
        trace = obs.export.chrome_trace(records,
                                        align=not args.no_align)
        text = json.dumps(trace, default=str)
        if args.out == "-":
            print(text)
        else:
            with open(args.out, "w", encoding="utf-8") as f:
                f.write(text)
            n = trace["otherData"]["n_spans"]
            print(f"# dpxtrace: wrote {n} span(s) to {args.out}",
                  file=sys.stderr)
        return 0

    spans = obs.export.collect_spans(records)
    if args.cmd == "summarize":
        rows = obs.detect.summarize_ops(spans)
        print(_fmt_table(rows, ("op", "rank", "count", "median_ms",
                                "iqr_ms", "total_ms")))
        return 0

    # stragglers
    found = obs.detect.stragglers(spans, k=args.k)
    if not found:
        print("dpxtrace: no stragglers flagged")
        return 0
    print(_fmt_table(found, ("op", "rank", "median_ms",
                             "world_median_ms", "threshold_ms",
                             "excess_x", "n_ranks")))
    return 1 if args.fail_on_straggler else 0


if __name__ == "__main__":
    try:
        raise SystemExit(main())
    except BrokenPipeError:
        # `dpxtrace summarize | head` is a legitimate spelling — exit
        # quietly on a closed pipe instead of tracebacking
        import os as _os
        _os.close(2)
        raise SystemExit(0)
