"""dpxverify CLI — run the SPMD collective-order rules (analysis/spmd.py).

Usage::

    python -m tools.dpxverify                  # verify repo, baseline applied
    python -m tools.dpxverify --no-baseline    # every finding, raw
    python -m tools.dpxverify --write-baseline # accept current findings
    python -m tools.dpxverify --format github  # PR-inline annotations
    python -m tools.dpxverify path/ other.py   # restrict to paths

Exit code 0 = clean (no findings outside the committed baseline),
1 = new findings, 2 = a scanned file failed to parse. Same contract as
tools/dpxlint.py; CI runs ``python -m tools.dpxverify --baseline`` in
the no-install lint job (.github/workflows/tier1.yml). Rule catalog
(DPX009-011) is docs/analysis.md.

Like dpxlint, this module must run jax-free: analysis.spmd imports only
stdlib + analysis.lint (stdlib + obs.export, also stdlib).
"""

from __future__ import annotations

import argparse
import os
import sys


def _load_spmd():
    """Import analysis.spmd WITHOUT executing the package __init__ (which
    pulls jax): fabricate a lightweight parent package so the module's
    relative imports resolve against the source tree. setdefault keeps
    an already-imported real package (in-process test use) intact."""
    import importlib
    import types

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sys.path.insert(0, root)
    if "distributed_pytorch_tpu" not in sys.modules:
        pkg = types.ModuleType("distributed_pytorch_tpu")
        pkg.__path__ = [os.path.join(root, "distributed_pytorch_tpu")]
        sys.modules["distributed_pytorch_tpu"] = pkg
    return importlib.import_module(
        "distributed_pytorch_tpu.analysis.spmd")


def main(argv=None) -> int:
    spmd = _load_spmd()
    lint = spmd._lint

    ap = argparse.ArgumentParser(prog="dpxverify", description=__doc__)
    ap.add_argument("paths", nargs="*",
                    help="files/dirs to verify (default: repo root)")
    ap.add_argument("--baseline", nargs="?", const=spmd.DEFAULT_BASELINE,
                    default=spmd.DEFAULT_BASELINE, metavar="FILE",
                    help="baseline file (default: committed baseline)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report every finding, ignoring the baseline")
    ap.add_argument("--write-baseline", action="store_true",
                    help="write current findings as the new baseline")
    ap.add_argument("--format", choices=lint.FORMATS, default="text",
                    help="output format (default: text)")
    args = ap.parse_args(argv)

    root = lint.repo_root()
    findings = spmd.verify_paths(args.paths or None, root=root)

    parse_failures = [f for f in findings if f.rule == "DPX000"]
    findings = [f for f in findings if f.rule != "DPX000"]

    baseline_path = (args.baseline if os.path.isabs(args.baseline)
                     else os.path.join(root, args.baseline))
    if args.write_baseline:
        lint.save_baseline(baseline_path, findings)
        print(f"dpxverify: wrote {len(findings)} finding(s) to "
              f"{os.path.relpath(baseline_path, root)}")
        if parse_failures:
            for f in parse_failures:
                print(str(f), file=sys.stderr)
            return 2
        return 0

    if not args.no_baseline and os.path.exists(baseline_path):
        findings = lint.apply_baseline(
            findings, lint.load_baseline(baseline_path))

    if args.format == "text":
        for f in parse_failures:
            print(str(f), file=sys.stderr)
        for f in findings:
            print(str(f))
    else:
        out = lint.format_findings(parse_failures + findings, args.format)
        if out:
            print(out)
    if parse_failures:
        return 2
    if findings:
        print(f"dpxverify: {len(findings)} new finding(s) — fix, add "
              "'# dpxlint: disable=DPXnnn <reason>', or re-baseline "
              "(docs/analysis.md)", file=sys.stderr)
        return 1
    if args.format == "text":
        print("dpxverify: clean")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
