"""Regenerate docs/env_vars.md from the typed env registry.

Usage: ``python -m tools.gen_env_docs`` (writes the file) or
``--check`` (exit 1 when the committed file is stale — the tier-1 test
tests/test_dpxlint.py::test_env_docs_current runs this in-process).
"""

from __future__ import annotations

import argparse
import os
import sys

DOC_PATH = os.path.join("docs", "env_vars.md")


def main(argv=None) -> int:
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sys.path.insert(0, root)
    from distributed_pytorch_tpu.runtime import env

    ap = argparse.ArgumentParser(prog="gen_env_docs", description=__doc__)
    ap.add_argument("--check", action="store_true",
                    help="verify docs/env_vars.md is current; write "
                         "nothing")
    args = ap.parse_args(argv)

    want = env.generate_docs()
    path = os.path.join(root, DOC_PATH)
    have = open(path).read() if os.path.exists(path) else None
    if args.check:
        if have != want:
            print(f"{DOC_PATH} is stale — run python -m tools.gen_env_docs",
                  file=sys.stderr)
            return 1
        print(f"{DOC_PATH} is current")
        return 0
    with open(path, "w") as f:
        f.write(want)
    print(f"wrote {DOC_PATH} ({len(env.REGISTRY)} variables)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
