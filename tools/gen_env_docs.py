"""Regenerate docs/env_vars.md from the typed env registry.

Usage: ``python -m tools.gen_env_docs`` (writes the file) or
``--check`` (exit 1 when the committed file is stale — the tier-1 test
tests/test_dpxlint.py::test_env_docs_current runs this in-process).
"""

from __future__ import annotations

import argparse
import os
import sys

DOC_PATH = os.path.join("docs", "env_vars.md")


def _load_env():
    """Import runtime.env WITHOUT executing the package __init__s
    (runtime/__init__ pulls jax): fabricate BOTH lightweight parents so
    the CI drift-gate step runs in the no-install lint job. setdefault
    keeps already-imported real packages (in-process test use) intact;
    env.py itself has no relative imports."""
    import importlib
    import types

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sys.path.insert(0, root)
    pkg_dir = os.path.join(root, "distributed_pytorch_tpu")
    for name, path in (("distributed_pytorch_tpu", pkg_dir),
                       ("distributed_pytorch_tpu.runtime",
                        os.path.join(pkg_dir, "runtime"))):
        if name not in sys.modules:
            mod = types.ModuleType(name)
            mod.__path__ = [path]
            sys.modules[name] = mod
    return importlib.import_module(
        "distributed_pytorch_tpu.runtime.env")


def main(argv=None) -> int:
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = _load_env()

    ap = argparse.ArgumentParser(prog="gen_env_docs", description=__doc__)
    ap.add_argument("--check", action="store_true",
                    help="verify docs/env_vars.md is current; write "
                         "nothing")
    args = ap.parse_args(argv)

    want = env.generate_docs()
    path = os.path.join(root, DOC_PATH)
    have = open(path).read() if os.path.exists(path) else None
    if args.check:
        if have != want:
            print(f"{DOC_PATH} is stale — run python -m tools.gen_env_docs",
                  file=sys.stderr)
            return 1
        print(f"{DOC_PATH} is current")
        return 0
    with open(path, "w") as f:
        f.write(want)
    print(f"wrote {DOC_PATH} ({len(env.REGISTRY)} variables)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
