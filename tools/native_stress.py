"""Pure-ctypes stress driver for native/dpxhost.cpp — the sanitizer
workhorse (docs/analysis.md).

Drives every exported native op (ring allreduce f32/f64 x sum/max/min,
quantized ring, rooted reduce/gather/broadcast, barrier, CRC32C, abort
teardown) across a real multi-process TCP group, verifying numerics —
WITHOUT importing jax or the package. That matters because the
ASan/UBSan/TSan runs preload the sanitizer runtime into an
uninstrumented python: jaxlib's MLIR bindings abort under the ASan
``__cxa_throw`` interceptor and wedge under TSan, so the instrumented
native library must be exercised by a driver whose process never touches
jax. (The uninstrumented-suite ASan run still covers the native code on
the jax-free paths — tests/test_host_backend.py's native tests pass
under ASan — but THIS driver is the one that works under all three
sanitizers.)

Usage::

    python tools/native_stress.py --lib native/libdpxhost-asan.so \
        --world 4 --iters 2

Exit 0 = every check on every rank passed. Run under a sanitizer via::

    ASAN_OPTIONS=detect_leaks=0 \
    python tools/native_stress.py --lib native/libdpxhost-asan.so \
        --preload "$(g++ -print-file-name=libasan.so)"

``--preload`` sets LD_PRELOAD for the WORKER processes only: the
harness parent stays uninstrumented (a TSan-preloaded CPython parent
wedges before spawn on this toolchain; instrumenting the harness buys
nothing anyway — the code under test runs in the workers).
(detect_leaks=0: CPython itself "leaks" interned objects by design; the
native library's own allocations are all vector/RAII-scoped.)
"""

from __future__ import annotations

import argparse
import ctypes
import os
import socket
import subprocess
import sys

import numpy as np

#: Standard CRC32C check value (RFC 3720): crc of b"123456789".
CRC32C_CHECK = 0xE3069283

SIZES = (1, 3, 255, 1024, 65536 + 7)


def load(lib_path: str):
    lib = ctypes.CDLL(lib_path)
    lib.dpx_comm_init.restype = ctypes.c_void_p
    lib.dpx_comm_init.argtypes = [ctypes.c_char_p] + [ctypes.c_int] * 4
    lib.dpx_comm_destroy.argtypes = [ctypes.c_void_p]
    lib.dpx_comm_abort.argtypes = [ctypes.c_void_p]
    lib.dpx_set_timeout_ms.argtypes = [ctypes.c_void_p, ctypes.c_int]
    f32p = ctypes.POINTER(ctypes.c_float)
    f64p = ctypes.POINTER(ctypes.c_double)
    lib.dpx_allreduce_f32_op.argtypes = [ctypes.c_void_p, f32p,
                                         ctypes.c_int64, ctypes.c_int]
    lib.dpx_allreduce_f64_op.argtypes = [ctypes.c_void_p, f64p,
                                         ctypes.c_int64, ctypes.c_int]
    lib.dpx_allreduce_q8.argtypes = [ctypes.c_void_p, f32p,
                                     ctypes.c_int64, ctypes.c_int,
                                     ctypes.c_int]
    lib.dpx_reduce_scatter_q8.argtypes = [ctypes.c_void_p, f32p,
                                          ctypes.c_int64, ctypes.c_int,
                                          ctypes.c_int]
    lib.dpx_allgather_q8.argtypes = [ctypes.c_void_p, f32p,
                                     ctypes.c_int64, ctypes.c_int,
                                     ctypes.c_int]
    for name in ("dpx_allreduce_qn", "dpx_reduce_scatter_qn",
                 "dpx_allgather_qn"):
        fn = getattr(lib, name)
        fn.argtypes = [ctypes.c_void_p, f32p, ctypes.c_int64,
                       ctypes.c_int, ctypes.c_int, ctypes.c_int]
        fn.restype = ctypes.c_int
    lib.dpx_reduce_f32.argtypes = [ctypes.c_void_p, f32p, ctypes.c_int64]
    lib.dpx_gather.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                               ctypes.c_int64, ctypes.c_char_p]
    lib.dpx_broadcast.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                  ctypes.c_int64, ctypes.c_int]
    lib.dpx_barrier.argtypes = [ctypes.c_void_p]
    lib.dpx_crc32c.argtypes = [ctypes.c_void_p, ctypes.c_int64]
    lib.dpx_crc32c.restype = ctypes.c_uint32
    for f in ("dpx_allreduce_f32_op", "dpx_allreduce_f64_op",
              "dpx_allreduce_q8", "dpx_reduce_scatter_q8",
              "dpx_allgather_q8", "dpx_reduce_f32", "dpx_gather",
              "dpx_broadcast", "dpx_barrier"):
        getattr(lib, f).restype = ctypes.c_int
    return lib


def check(cond: bool, what: str) -> None:
    if not cond:
        raise AssertionError(what)


def f32ptr(a):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_float))


def worker(lib_path: str, base_port: int, rank: int, world: int,
           iters: int) -> int:
    lib = load(lib_path)
    crc = lib.dpx_crc32c(b"123456789", 9)
    check(crc == CRC32C_CHECK,
          f"crc32c check value {crc:#x} != {CRC32C_CHECK:#x}")

    h = lib.dpx_comm_init(b"127.0.0.1", base_port, rank, world, 20000)
    check(bool(h), "rendezvous failed")
    lib.dpx_set_timeout_ms(h, 30000)
    tri = world * (world + 1) / 2.0
    for it in range(iters):
        for n in SIZES:
            # sum / max / min rings, f32 and f64
            a = np.full(n, rank + 1, np.float32)
            check(lib.dpx_allreduce_f32_op(h, f32ptr(a), n, 0) == 0,
                  "allreduce f32 sum rc")
            check(float(a[0]) == tri and float(a[-1]) == tri,
                  "allreduce f32 sum value")
            d = np.full(n, rank + 1, np.float64)
            dp = d.ctypes.data_as(ctypes.POINTER(ctypes.c_double))
            check(lib.dpx_allreduce_f64_op(h, dp, n, 1) == 0,
                  "allreduce f64 max rc")
            check(float(d[0]) == world, "allreduce f64 max value")
            m = np.full(n, rank + 1, np.float32)
            check(lib.dpx_allreduce_f32_op(h, f32ptr(m), n, 2) == 0,
                  "allreduce f32 min rc")
            check(float(m[-1]) == 1.0, "allreduce f32 min value")

            # quantized ring: lossy sum, but bit-identical across ranks
            rng = np.random.default_rng(1000 + n + it)
            base = rng.standard_normal((world, n)).astype(np.float32)
            q = base[rank].copy()
            check(lib.dpx_allreduce_q8(h, f32ptr(q), n, 64, 4) == 0,
                  "allreduce_q8 rc")
            want = base.sum(axis=0)
            # one quant step per hop; partial-sum amax can reach
            # world*amax, and there are ~world hops => world^2 bound
            tol = 2.0 * world * world * (np.abs(base).max() / 127.0) + 1e-6
            check(float(np.abs(q - want).max()) <= tol,
                  f"q8 error beyond bound at n={n}")
            # cross-rank bit-identity: gather every rank's result CRC
            qc = np.uint32(lib.dpx_crc32c(
                q.ctypes.data_as(ctypes.c_void_p), q.nbytes))
            rbuf = (np.zeros(world, np.uint32) if rank == 0 else None)
            rc = lib.dpx_gather(
                h, qc.tobytes(), 4,
                rbuf.ctypes.data_as(ctypes.c_char_p)
                if rank == 0 else None)
            check(rc == 0, "gather rc")
            if rank == 0:
                check(len(set(rbuf.tolist())) == 1,
                      f"q8 results not bit-identical: {rbuf}")

            # the ring's two legs standalone (sharded-update dataflow):
            # composed they must equal dpx_allreduce_q8 bit for bit
            s2 = base[rank].copy()
            check(lib.dpx_reduce_scatter_q8(h, f32ptr(s2), n, 64, 4)
                  == 0, "reduce_scatter_q8 rc")
            check(lib.dpx_allgather_q8(h, f32ptr(s2), n, 64, 4) == 0,
                  "allgather_q8 rc")
            check(np.array_equal(s2, q),
                  f"rs+ag != allreduce_q8 at n={n}")

            # the q8 wrapper must BE the qn family at bits=8
            s8 = base[rank].copy()
            check(lib.dpx_allreduce_qn(h, f32ptr(s8), n, 64, 4, 8) == 0,
                  "allreduce_qn(8) rc")
            check(np.array_equal(s8, q), f"qn(8) != q8 at n={n}")

            # 4-bit wire: coarser grid (levels=7), same invariants —
            # bounded error, cross-rank bit-identity, legs compose
            q4 = base[rank].copy()
            check(lib.dpx_allreduce_qn(h, f32ptr(q4), n, 64, 4, 4) == 0,
                  "allreduce_qn(4) rc")
            tol4 = 2.0 * world * world * (np.abs(base).max() / 7.0) + 1e-6
            check(float(np.abs(q4 - want).max()) <= tol4,
                  f"q4 error beyond bound at n={n}")
            qc4 = np.uint32(lib.dpx_crc32c(
                q4.ctypes.data_as(ctypes.c_void_p), q4.nbytes))
            rbuf4 = (np.zeros(world, np.uint32) if rank == 0 else None)
            check(lib.dpx_gather(
                h, qc4.tobytes(), 4,
                rbuf4.ctypes.data_as(ctypes.c_char_p)
                if rank == 0 else None) == 0, "gather rc (q4)")
            if rank == 0:
                check(len(set(rbuf4.tolist())) == 1,
                      f"q4 results not bit-identical: {rbuf4}")
            s4 = base[rank].copy()
            check(lib.dpx_reduce_scatter_qn(h, f32ptr(s4), n, 64, 4, 4)
                  == 0, "reduce_scatter_qn(4) rc")
            check(lib.dpx_allgather_qn(h, f32ptr(s4), n, 64, 4, 4) == 0,
                  "allgather_qn(4) rc")
            check(np.array_equal(s4, q4),
                  f"rs+ag != allreduce_qn(4) at n={n}")

            # rooted reduce + broadcast round trip
            r = np.full(n, float(rank), np.float32)
            check(lib.dpx_reduce_f32(h, f32ptr(r), n) == 0, "reduce rc")
            if rank == 0:
                check(float(r[0]) == world * (world - 1) / 2.0,
                      "reduce value")
            b = (np.arange(n, dtype=np.float32) if rank == 0
                 else np.zeros(n, np.float32))
            check(lib.dpx_broadcast(
                h, b.ctypes.data_as(ctypes.c_char_p), b.nbytes, 0) == 0,
                "broadcast rc")
            check(float(b[-1]) == n - 1, "broadcast value")
        check(lib.dpx_barrier(h) == 0, "barrier rc")

    # hierarchical two-level legs (comm/hier.py's native substrate):
    # sub-groups of L=2 consecutive ranks rendezvous on offset ports,
    # exact rooted reduce to each leader, q4 ring between leaders,
    # exact broadcast back — exercising concurrent groups + the qn
    # codec under the sanitizer. Mirrors HierRing's port scheme.
    if world % 2 == 0 and world >= 4:
        L = 2
        nh = world // L
        host_id, local_rank = rank // L, rank % L
        local_base = base_port + world + 1 + host_id * L
        hl = lib.dpx_comm_init(b"127.0.0.1", local_base, local_rank, L,
                               20000)
        check(bool(hl), "local sub-group rendezvous failed")
        lib.dpx_set_timeout_ms(hl, 30000)
        hlead = None
        if local_rank == 0:
            leader_base = base_port + 2 * world + 1
            hlead = lib.dpx_comm_init(b"127.0.0.1", leader_base, host_id,
                                      nh, 20000)
            check(bool(hlead), "leader sub-group rendezvous failed")
            lib.dpx_set_timeout_ms(hlead, 30000)
        n = 4096 + 13
        rng = np.random.default_rng(77)
        hbase = rng.standard_normal((world, n)).astype(np.float32)
        x = hbase[rank].copy()
        check(lib.dpx_reduce_f32(hl, f32ptr(x), n) == 0,
              "hier local reduce rc")
        if hlead is not None:
            check(lib.dpx_allreduce_qn(hlead, f32ptr(x), n, 64, 4, 4)
                  == 0, "hier leader allreduce_qn(4) rc")
        check(lib.dpx_broadcast(
            hl, x.ctypes.data_as(ctypes.c_char_p), x.nbytes, 0) == 0,
            "hier local broadcast rc")
        want = hbase.sum(axis=0)
        tol = 2.0 * nh * nh * (np.abs(want).max() / 7.0) + 1e-6
        check(float(np.abs(x - want).max()) <= tol,
              "hier result beyond q4 bound")
        # cross-rank bit-identity over the WHOLE world
        xc = np.uint32(lib.dpx_crc32c(
            x.ctypes.data_as(ctypes.c_void_p), x.nbytes))
        rb = (np.zeros(world, np.uint32) if rank == 0 else None)
        check(lib.dpx_gather(
            h, xc.tobytes(), 4,
            rb.ctypes.data_as(ctypes.c_char_p) if rank == 0 else None)
            == 0, "gather rc (hier)")
        if rank == 0:
            check(len(set(rb.tolist())) == 1,
                  f"hier results not bit-identical: {rb}")
        if hlead is not None:
            lib.dpx_comm_destroy(hlead)
        lib.dpx_comm_destroy(hl)
    lib.dpx_comm_destroy(h)

    # abort-path teardown: a second group is aborted, every later op must
    # fail fast (exercises close/shutdown paths under the sanitizer).
    # Ports beyond the hier sub-groups' range (base+world+1 .. base+2W+nh)
    # so no listener is re-bound while a peer still races its teardown.
    h2 = lib.dpx_comm_init(b"127.0.0.1", base_port + 3 * world + 2, rank,
                           world, 20000)
    check(bool(h2), "second rendezvous failed")
    lib.dpx_comm_abort(h2)
    a = np.ones(8, np.float32)
    check(lib.dpx_allreduce_f32_op(h2, f32ptr(a), 8, 0) != 0,
          "op on aborted comm must fail")
    lib.dpx_comm_destroy(h2)
    print(f"rank {rank}: ok", flush=True)
    return 0


def find_free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="native_stress",
                                 description=__doc__)
    ap.add_argument("--lib", default="native/libdpxhost.so")
    ap.add_argument("--world", type=int, default=4)
    ap.add_argument("--iters", type=int, default=2)
    ap.add_argument("--timeout", type=float, default=240.0)
    ap.add_argument("--preload", default=None, metavar="LIBSAN",
                    help="LD_PRELOAD for the worker processes (sanitizer "
                         "runtime); the parent stays uninstrumented")
    ap.add_argument("--worker", nargs=4, metavar=("PORT", "RANK",
                                                  "WORLD", "ITERS"),
                    help=argparse.SUPPRESS)
    args = ap.parse_args(argv)

    if args.worker:
        port, rank, world, iters = map(int, args.worker)
        return worker(args.lib, port, rank, world, iters)

    port = find_free_port()
    child_env = dict(os.environ)  # dpxlint: disable=DPX002 verbatim child-env passthrough; this harness must not import the jax-backed registry
    if args.preload:
        child_env["LD_PRELOAD"] = args.preload
    procs = [subprocess.Popen(
        [sys.executable, __file__, "--lib", args.lib, "--worker",
         str(port), str(r), str(args.world), str(args.iters)],
        env=child_env)
        for r in range(args.world)]
    rc = 0
    try:
        for p in procs:
            p.wait(timeout=args.timeout)
            rc |= p.returncode
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        print("native_stress: HUNG", file=sys.stderr)
        return 3
    print(f"native_stress: {'ok' if rc == 0 else 'FAILED'} "
          f"(world={args.world}, lib={args.lib})")
    return rc


if __name__ == "__main__":
    raise SystemExit(main())
