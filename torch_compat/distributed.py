"""Drop-in ``distributed`` module for the reference's torch workloads.

This is the compatibility front door that lets the literal reference
workload (``/root/reference/min_DDP.py``, which binds via ``import
distributed as dist`` at min_DDP.py:7) run **unmodified** on this
framework: put this directory on ``PYTHONPATH`` and the 18-function API
(reference distributed.py:32-187) resolves here instead of to
torch.distributed/c10d/NCCL.

Torch is used only as the *tensor* library (the workload's own compute);
every distributed concern — process spawn, rendezvous, collectives,
gradient synchronization, data sharding — is served by this framework:

- transport: the native C++ host group (``native/dpxhost.cpp``: TCP
  rendezvous + ring reduce-scatter/all-gather allreduce + hub rooted
  ops), the same backend that replaces Gloo/TCPStore for the per-rank
  front door (SURVEY.md §2.3 rows 2-3),
- DDP: a grad-hook wrapper (:class:`DistributedDataParallel` below)
  reproducing torch DDP's observable contract — constructor broadcast of
  params/buffers from rank 0, gradient averaging during backward
  (reference distributed.py:112-115 and SURVEY.md §2.3 row 4),
- sampler: rank-strided, padded, ``set_epoch``-reseeded index sampler
  with torch ``DistributedSampler`` semantics (reference
  distributed.py:105-108),
- device model: world size comes from ``DPX_VISIBLE_DEVICES`` (the
  framework's CUDA_VISIBLE_DEVICES analog, runtime/context.py) when set,
  else ``torch.cuda.device_count()`` exactly like reference
  distributed.py:41.

Semantics matched function-by-function against reference
``distributed.py`` (file:line cited on each function); the quirks are
deliberately preserved: ``reduce`` leaves non-root buffers untouched
(:136-144), ``gather`` returns zeros on non-primary ranks (:147-160),
``launch`` passes world_size=0 to the worker on CPU-only hosts (:57-58).
"""

from __future__ import annotations

# dpxlint: disable-file=DPX002 standalone shim: must import under bare torch with no jax, so it cannot use the runtime/env.py registry (vars are still documented there)

import math
import os
import socket
import sys
import threading
from contextlib import closing

import numpy as np
import torch

# Resolve the framework package regardless of where the workload runs from.
_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)

_COMM = None  # the native HostComm for this rank process, set by init

_COMM_ERRORS = ("CommError", "CommPeerDied", "CommTimeout", "CommCorrupt")


def __getattr__(name):
    """Re-export the typed comm-failure hierarchy (PEP 562, lazily — the
    framework package pulls in jax, which the literal torch workload must
    not pay for at import time). A collective on a dead/wedged peer
    raises these instead of hanging; ``DPX_COMM_TIMEOUT_MS`` bounds every
    collective (see docs/failures.md)."""
    if name in _COMM_ERRORS:
        from distributed_pytorch_tpu.runtime import native as _native
        return getattr(_native, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


def _device_count() -> int:
    """World size: ``DPX_VISIBLE_DEVICES`` count when set (the framework's
    device-gating env, mirroring the CUDA_VISIBLE_DEVICES workflow of
    reference README.md:109-119), else ``torch.cuda.device_count()``
    (reference distributed.py:41)."""
    spec = os.environ.get("DPX_VISIBLE_DEVICES")
    if spec is not None:
        return len([t for t in spec.split(",") if t.strip() != ""])
    return torch.cuda.device_count()


# launch (reference distributed.py:32-58)
def find_free_port():
    """Reference distributed.py:32-37."""
    with closing(socket.socket(socket.AF_INET, socket.SOCK_STREAM)) as s:
        s.bind(("", 0))
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        return s.getsockname()[1]


def _spawn_worker(rank, worker_fn, world_size, args):
    try:
        worker_fn(rank, world_size, *args)
    finally:
        cleanup()


def launch(worker_fn, *args):
    """Reference distributed.py:40-58: three branches on device count.

    world>1 spawns one OS process per device with the
    ``worker_fn(rank, world_size, *args)`` contract (spawn prepends the
    rank); world==1 runs in-process; world==0 (CPU) runs in-process with
    world_size=0 — both without a process group, exactly like the
    reference.
    """
    world_size = _device_count()

    if world_size > 1:
        if ("DPX_VISIBLE_DEVICES" not in os.environ
                and "CUDA_VISIBLE_DEVICES" not in os.environ):
            raise ValueError(
                "Devices not specified. Please set DPX_VISIBLE_DEVICES.")

        os.environ["MASTER_ADDR"] = "localhost"
        os.environ["MASTER_PORT"] = str(find_free_port())

        import multiprocessing as mp
        import time as _time
        ctx = mp.get_context("spawn")
        procs = []
        for rank in range(world_size):
            p = ctx.Process(target=_spawn_worker,
                            args=(rank, worker_fn, world_size, args))
            p.start()
            procs.append(p)
        # fail-fast supervision: poll so a crashed rank terminates its
        # still-blocked peers instead of waiting out collective timeouts
        failed = None
        while True:
            alive = False
            for rank, p in enumerate(procs):
                if p.is_alive():
                    alive = True
                elif p.exitcode != 0 and failed is None:
                    failed = (rank, p.exitcode)
            if failed or not alive:
                break
            _time.sleep(0.05)
        if failed:
            for p in procs:
                if p.is_alive():
                    p.terminate()
            for p in procs:  # SIGTERM grace, then SIGKILL — never hang here
                p.join(timeout=5)
                if p.is_alive():
                    p.kill()
                    p.join()
            rank, code = failed
            raise RuntimeError(
                f"worker process rank {rank} exited with code {code}")
        for p in procs:
            p.join()

    elif world_size == 1:
        worker_fn(0, world_size, *args)

    else:  # CPU training: world_size == 0 passed through, like :57-58
        worker_fn(0, world_size, *args)


# distributed training functions (reference distributed.py:62-101)
def init_process_group(rank, world_size, backend=None):
    """Reference distributed.py:62-66: rendezvous through the env vars set
    by launch (MASTER_ADDR/MASTER_PORT), but over the native TCP group
    instead of c10d. ``backend`` is accepted for signature parity; the
    only backend is the native host group."""
    global _COMM
    from distributed_pytorch_tpu.runtime.native import HostComm

    addr = os.environ.get("MASTER_ADDR", "localhost")
    port = int(os.environ.get("MASTER_PORT", "29500"))
    _COMM = HostComm(addr, port, rank, world_size)


def is_dist_avail_and_initialized():
    """Reference distributed.py:69-74."""
    return _COMM is not None


def cleanup():
    """Reference distributed.py:77-79."""
    global _COMM
    if _COMM is not None:
        _COMM.close()
        _COMM = None


def get_rank():
    """Reference distributed.py:82-85."""
    if not is_dist_avail_and_initialized():
        return 0
    return _COMM.rank


def get_device():
    """Reference distributed.py:88-91. Torch compute runs on CPU here
    (torch has no TPU backend in this environment); with CUDA present the
    reference mapping rank->cuda:rank is preserved."""
    if torch.cuda.is_available():
        return torch.device(f"cuda:{get_rank()}")
    return torch.device("cpu")


def is_primary():
    """Reference distributed.py:94-95."""
    return get_rank() == 0


def get_world_size():
    """Reference distributed.py:98-101."""
    if not is_dist_avail_and_initialized():
        return 1
    return _COMM.world


# data loading stuff (reference distributed.py:105-108)
class _ShardedSampler:
    """torch ``DistributedSampler`` contract (reference
    distributed.py:105-108; used with set_epoch at min_DDP.py:82-83):
    pad indices to a multiple of world, stride them rank-wise, reshuffle
    per epoch from a seed+epoch generator."""

    def __init__(self, dataset, shuffle=True, seed=0):
        self.n = len(dataset)
        self.rank = get_rank()
        self.world = max(get_world_size(), 1)
        self.shuffle = shuffle
        self.seed = seed
        self.epoch = 0
        self.num_samples = math.ceil(self.n / self.world)

    def set_epoch(self, epoch):
        self.epoch = epoch

    def __iter__(self):
        if self.shuffle:
            g = torch.Generator().manual_seed(self.seed + self.epoch)
            order = torch.randperm(self.n, generator=g).tolist()
        else:
            order = list(range(self.n))
        total = self.num_samples * self.world
        pad = total - len(order)
        if pad > 0:  # repeat-wrap, valid even when pad > len(order)
            order = (order * (pad // len(order) + 2))[:total]
        return iter(order[self.rank:total:self.world])

    def __len__(self):
        return self.num_samples


def data_sampler(dataset, distributed, shuffle):
    """Reference distributed.py:105-108."""
    if distributed:
        return _ShardedSampler(dataset, shuffle=shuffle)
    return None


# model wrapping (reference distributed.py:112-115)
class DistributedDataParallel(torch.nn.Module):
    """Grad-hook DDP over the native host group, with bucketed, overlapped
    gradient synchronization.

    Reproduces the torch DDP contract the reference relies on
    (distributed.py:27,114 and SURVEY.md §2.3 row 4): parameters and
    buffers broadcast from rank 0 at construction; during ``backward``
    gradients are all-reduced and averaged across ranks as they are
    produced, so ``optimizer.step()`` sees synchronized gradients with no
    extra calls in the training loop (min_DDP.py:102-104).

    Like the torch reducer, parameters are grouped into size-capped flat
    buckets in REVERSE registration order (the order autograd produces
    gradients), one bucket never mixing dtypes (gradients reduce in their
    native dtype — no silent downcast); each bucket's single ring
    all-reduce is issued by a communication thread as soon as the bucket's
    gradients are all accumulated, overlapping communication with the rest
    of backward. Buckets are processed in a fixed order on every rank, so
    the ring collectives can never interleave differently across ranks.
    An autograd end-of-backward callback joins the thread, so
    ``backward()`` returns with fully synchronized gradients — and, like
    torch DDP without ``find_unused_parameters``, raises if some
    requires_grad parameter produced no gradient (silently skipping its
    bucket would let ranks diverge). ``bucket_cap_mb=0`` degrades to one
    bucket per parameter (the unbucketed baseline, kept for measurement).

    ``grad_reduce="quant"`` (or env ``DPX_GRAD_REDUCE=quant``, so the
    LITERAL unmodified reference workload can opt in from the shell —
    flag parity with ``make_train_step(grad_reduce=...)``): float32
    buckets ride the native chunk-pipelined block-int8 ring
    (``dpx_allreduce_q8``, ~4x less TCP traffic) with a per-bucket
    error-feedback residual carried across backward passes; non-f32
    buckets and all broadcasts stay exact. The reduced bucket is
    bit-identical on every rank, so replicas cannot drift.
    """

    def __init__(self, module, device_ids=None, bucket_cap_mb=25,
                 grad_reduce=None, **kwargs):
        super().__init__()
        self.module = module
        self._world = get_world_size()
        self._broadcast_buffers = kwargs.get("broadcast_buffers", True)
        if grad_reduce is None:
            grad_reduce = os.environ.get("DPX_GRAD_REDUCE", "mean")
        if grad_reduce not in ("mean", "quant", "int8"):
            raise ValueError(f"grad_reduce must be mean|quant|int8, "
                             f"got {grad_reduce!r}")
        self._quant = grad_reduce in ("quant", "int8")
        self._bucket_ef = {}  # bucket index -> ErrorFeedback residual
        if self._world > 1:
            with torch.no_grad():
                for t in list(module.parameters()) + list(module.buffers()):
                    _broadcast_inplace(t)
            self._build_buckets(bucket_cap_mb)
            self._lock = threading.Lock()
            self._ready = [0] * len(self._buckets)
            self._total_ready = 0
            self._bucket_done = None
            self._worker = None
            self._worker_exc = None
            self._abort = False
            self._hooks = [
                p.register_post_accumulate_grad_hook(self._on_grad)
                for p in self.module.parameters() if p.requires_grad]

    def _build_buckets(self, cap_mb: float) -> None:
        params = [p for p in self.module.parameters() if p.requires_grad]
        cap = cap_mb * (1 << 20)
        self._buckets, cur, size = [], [], 0
        for p in reversed(params):  # autograd's gradient-ready order
            nbytes = p.numel() * p.element_size()
            if cur and (size + nbytes > cap or p.dtype != cur[-1].dtype):
                self._buckets.append(cur)
                cur, size = [], 0
            cur.append(p)
            size += nbytes
        if cur:
            self._buckets.append(cur)
        self._param_bucket = {id(p): bi
                              for bi, b in enumerate(self._buckets)
                              for p in b}
        self._n_params = len(params)

    def _reduce_bucket(self, bucket, bucket_idx=None) -> None:
        grads = [p.grad for p in bucket]
        flat = np.concatenate([_to_np(g).ravel() for g in grads])
        if self._quant and flat.dtype == np.float32:
            from distributed_pytorch_tpu.ops.quant import ErrorFeedback
            ef = self._bucket_ef.setdefault(bucket_idx, ErrorFeedback())
            flat = ef.compensate(flat)
            # dpxlint: disable=DPX001 the grad-sync worker thread IS this front door's rank execution context (torch DDP's reducer-thread model); ordering is pinned by the bucket_done events
            out = _COMM.allreduce_q8(flat)
        else:
            # dpxlint: disable=DPX001 see above: reducer-thread model, bucket-ordered
            out = _COMM.allreduce(flat)
        if out is not flat:
            flat = out
        flat /= self._world
        off = 0
        with torch.no_grad():
            for g in grads:
                n = g.numel()
                g.copy_(torch.from_numpy(
                    flat[off:off + n].reshape(tuple(g.shape))).to(
                        device=g.device, dtype=g.dtype))
                off += n

    def _worker_main(self, done_events) -> None:
        try:
            for bi, ev in enumerate(done_events):
                ev.wait()
                if self._abort:
                    return
                self._reduce_bucket(self._buckets[bi], bucket_idx=bi)
        except Exception as e:  # noqa: BLE001 — re-raised at finalize
            self._worker_exc = e

    def _on_grad(self, param) -> None:
        with self._lock:
            if self._worker is None:  # first gradient of this backward
                self._bucket_done = [threading.Event()
                                     for _ in self._buckets]
                self._worker_exc = None
                self._abort = False
                self._worker = threading.Thread(
                    target=self._worker_main, args=(self._bucket_done,),
                    name="dpx-ddp-reducer", daemon=True)
                self._worker.start()
                # runs on the autograd engine once this backward pass
                # completes, whether or not every hook fired
                torch.autograd.Variable._execution_engine.queue_callback(
                    self._finalize_backward)
            bi = self._param_bucket[id(param)]
            self._ready[bi] += 1
            if self._ready[bi] == len(self._buckets[bi]):
                self._bucket_done[bi].set()
            self._total_ready += 1

    def _finalize_backward(self) -> None:
        """End-of-backward: join the comm thread so grads are synchronized
        when ``backward()`` returns; detect incomplete backwards (a
        requires_grad parameter that produced no gradient) instead of
        wedging on the missing bucket."""
        with self._lock:
            worker, events = self._worker, self._bucket_done
            if worker is None:
                return
            incomplete = self._total_ready != self._n_params
            if incomplete:
                self._abort = True
                for ev in events:
                    ev.set()  # unblock the worker so it can exit
            self._worker = None
            self._ready = [0] * len(self._buckets)
            self._total_ready = 0
        worker.join()
        if self._worker_exc is not None:
            raise self._worker_exc
        if incomplete:
            raise RuntimeError(
                "DistributedDataParallel: some requires_grad parameters "
                "received no gradient in this backward pass; gradient "
                "buckets were left unsynchronized (torch DDP raises here "
                "too unless find_unused_parameters is used — exclude the "
                "unused parameters or set requires_grad=False)")

    def forward(self, *args, **kwargs):
        # torch DDP re-broadcasts buffers (e.g. BatchNorm running stats)
        # from rank 0 before each forward when broadcast_buffers=True
        if self._world > 1 and self._broadcast_buffers:
            with torch.no_grad():
                for b in self.module.buffers():
                    _broadcast_inplace(b)
        return self.module(*args, **kwargs)


def prepare_ddp_model(model, device_ids, *args, **kwargs):
    """Reference distributed.py:112-115."""
    if get_world_size() > 1:
        model = DistributedDataParallel(model, device_ids=device_ids,
                                        *args, **kwargs)
    return model


# synchronization functions (reference distributed.py:119-187)
def _to_np(tensor) -> np.ndarray:
    return tensor.detach().cpu().numpy()


def _broadcast_inplace(tensor, src=0):
    out = _COMM.broadcast(np.ascontiguousarray(_to_np(tensor)), src=src)
    with torch.no_grad():
        tensor.copy_(torch.from_numpy(out).view_as(tensor))
    return tensor


def all_reduce(tensor, op="sum"):
    """Reference distributed.py:119-133: in-place sum or sum/world on
    every rank; identity at world==1; ValueError otherwise."""
    world_size = get_world_size()
    if world_size == 1:
        # reference distributed.py:122-123 returns before validating op
        return tensor
    if op == "sum":
        work = _to_np(tensor).astype(np.float64)
        _COMM.allreduce(work)
    elif op == "avg":
        work = _to_np(tensor).astype(np.float64)
        _COMM.allreduce(work)
        work /= world_size
    else:
        # Error-message parity with reference distributed.py:131 —
        # callers matching on the message see identical behavior (pinned
        # by tests/test_torch_compat.py::test_all_reduce_invalid_op_message).
        raise ValueError(f'"{op}" is an invalid reduce operation!')
    with torch.no_grad():
        tensor.copy_(torch.from_numpy(work).to(tensor.dtype).view_as(tensor))
    return tensor


def reduce(tensor, op="sum"):
    """Reference distributed.py:136-144: rooted sum to rank 0, in place on
    the root; non-root buffers returned untouched (their contents are
    backend-defined there — here they keep the local value). Only SUM is
    supported (the reference forwards ``op`` to c10d; this transport
    implements the one op the workload uses) — anything else raises
    rather than silently summing."""
    world_size = get_world_size()
    if world_size == 1:
        return tensor
    if op != "sum":
        raise ValueError(f'"{op}" is an invalid reduce operation!')
    x = _to_np(tensor)
    if x.dtype == np.float32:
        # rooted hub reduce — one upload + root-side sum, no all-gather leg
        work = _COMM.reduce(np.ascontiguousarray(x))
    else:
        # other dtypes sum exactly in f64 over the ring
        work = _to_np(tensor).astype(np.float64)
        _COMM.allreduce(work)
    if is_primary():
        with torch.no_grad():
            tensor.copy_(
                torch.from_numpy(work).to(tensor.dtype).view_as(tensor))
    return tensor


def gather(data):
    """Reference distributed.py:147-160: rooted gather to rank 0; the
    returned list is the real values on the primary and the pre-allocated
    zeros on every other rank."""
    world_size = get_world_size()
    if world_size == 1:
        return [data]
    out = _COMM.gather(np.ascontiguousarray(_to_np(data)))
    if out is None:  # non-primary: the zeros it allocated, like :153
        return [torch.zeros_like(data) for _ in range(world_size)]
    return [torch.from_numpy(np.array(a)).to(data.dtype).view_as(data)
            for a in out]


def sync_params(params):
    """Reference distributed.py:163-170: broadcast each tensor from 0."""
    if is_dist_avail_and_initialized():
        for p in params:
            with torch.no_grad():
                _broadcast_inplace(p)


def barrier():
    """Reference distributed.py:173-177."""
    if get_world_size() == 1:
        return
    _COMM.barrier()


def wait_for_everyone():
    """Readability alias for :func:`barrier` (reference
    distributed.py:181-182)."""
    barrier()


def print_primary(*args, **kwargs):
    """Reference distributed.py:185-187."""
    if is_primary():
        print(*args, **kwargs)
